"""Pipeline parallelism: output and gradient parity with sequential."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hops_tpu.models.moe import sum_sown_losses
from hops_tpu.parallel import mesh as mesh_lib
from hops_tpu.parallel.pipeline import pipeline_apply, stack_stage_params

pytestmark = pytest.mark.slow  # heavy compiles / subprocess e2e (fast tier: -m 'not slow')

STAGES = 4
DIM = 16


def _stage_params(seed):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {
        "w": jax.random.normal(k1, (DIM, DIM)) * 0.3,
        "b": jax.random.normal(k2, (DIM,)) * 0.1,
    }


def stage_fn(params, h):
    return h + jnp.tanh(h @ params["w"] + params["b"])  # residual, shape-preserving


def _sequential(stages, x):
    for p in stages:
        x = stage_fn(p, x)
    return x


@pytest.fixture(scope="module")
def stage_mesh():
    return mesh_lib.make_mesh({"stage": STAGES}, devices=jax.devices()[:STAGES])


def test_pipeline_matches_sequential(stage_mesh):
    stages = [_stage_params(i) for i in range(STAGES)]
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(9), (8, DIM))
    out = pipeline_apply(stage_fn, stacked, x, stage_mesh)
    np.testing.assert_allclose(out, _sequential(stages, x), atol=1e-5, rtol=1e-5)


def test_pipeline_more_microbatches(stage_mesh):
    stages = [_stage_params(i) for i in range(STAGES)]
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (16, DIM))
    out = pipeline_apply(stage_fn, stacked, x, stage_mesh, num_microbatches=8)
    np.testing.assert_allclose(out, _sequential(stages, x), atol=1e-5, rtol=1e-5)


def test_pipeline_grads_match(stage_mesh):
    stages = [_stage_params(i) for i in range(STAGES)]
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(5), (8, DIM))

    def pp_loss(stacked):
        return pipeline_apply(stage_fn, stacked, x, stage_mesh).sum()

    def seq_loss(stacked):
        stages = [jax.tree.map(lambda p: p[i], stacked) for i in range(STAGES)]
        return _sequential(stages, x).sum()

    g_pp = jax.grad(pp_loss)(stacked)
    g_seq = jax.grad(seq_loss)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4), g_pp, g_seq
    )


def test_pipeline_rejects_bad_microbatch(stage_mesh):
    stacked = stack_stage_params([_stage_params(i) for i in range(STAGES)])
    x = jnp.zeros((6, DIM))
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(stage_fn, stacked, x, stage_mesh)


def test_heterogeneous_ingest_emit(stage_mesh):
    """Ring-boundary hooks: int input -> ingest embed -> stages -> emit
    projection with a different output dim."""
    stages = [_stage_params(i) for i in range(STAGES)]
    stacked = stack_stage_params(stages)
    table = jax.random.normal(jax.random.PRNGKey(1), (32, DIM)) * 0.2
    head = jax.random.normal(jax.random.PRNGKey(2), (DIM, 7)) * 0.2
    tokens = jax.random.randint(jax.random.PRNGKey(3), (8,), 0, 32)

    out = pipeline_apply(
        stage_fn, stacked, tokens, stage_mesh,
        ingest_fn=lambda p, t: p[t], ingest_params=table,
        emit_fn=lambda p, h: h @ p, emit_params=head,
    )
    ref = _sequential(stages, table[tokens]) @ head
    assert out.shape == (8, 7)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_chunk_stage_params_layout():
    from hops_tpu.parallel.pipeline import chunk_stage_params

    layers = [{"w": jnp.full((2, 2), i, jnp.float32)} for i in range(8)]
    chunked = chunk_stage_params(layers, 4)
    assert chunked["w"].shape == (4, 2, 2, 2)
    assert float(chunked["w"][1, 0, 0, 0]) == 2.0  # stage 1 holds layers 2,3
    with pytest.raises(ValueError, match="divisible"):
        chunk_stage_params(layers, 3)


def test_pipelined_transformer_lm_matches_dense(stage_mesh):
    """VERDICT r1 weak #5: a REAL TransformerLM (embed -> blocks -> head)
    through the pipeline, logits vs the dense model."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    model = TransformerLM(
        vocab_size=64, d_model=32, num_heads=4, num_layers=8,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=64,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    dense = model.apply({"params": params}, tokens)
    pp = pipelined_lm_apply(model, params, tokens, stage_mesh)
    np.testing.assert_allclose(pp, dense, atol=1e-4, rtol=1e-4)


def test_pipelined_lm_grads_match_dense(stage_mesh):
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 8), 0, 32)
    params = model.init(jax.random.PRNGKey(3), tokens)["params"]

    def dense_loss(p):
        return jnp.mean(model.apply({"params": p}, tokens) ** 2)

    def pp_loss(p):
        return jnp.mean(pipelined_lm_apply(model, p, tokens, stage_mesh) ** 2)

    g_dense = jax.grad(dense_loss)(params)
    g_pp = jax.grad(pp_loss)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4),
        g_dense, g_pp,
    )


def test_pipelined_moe_lm_matches_dense(stage_mesh):
    """VERDICT r2 weak #5: pp composes with ep — an LM with MoE blocks
    (moe_every=2) through the GPipe ring, logits vs the dense model.

    Parity tests route drop-free (top_k == num_experts): capacity-based
    token dropping is computed per batch, and under pp the batch a stage
    sees IS the microbatch — a semantic, documented difference
    (pipeline.py), not an implementation error. A dropping config is
    exercised separately for finiteness/shape."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    model = TransformerLM(
        vocab_size=64, d_model=32, num_heads=4, num_layers=8,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=64,
        moe_every=2, num_experts=4, moe_top_k=4,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(4), (8, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(5), tokens)["params"]
    dense = model.apply({"params": params}, tokens)
    pp = pipelined_lm_apply(model, params, tokens, stage_mesh)
    np.testing.assert_allclose(pp, dense, atol=1e-4, rtol=1e-4)


def test_pipelined_all_moe_lm_matches_dense(stage_mesh):
    """moe_every=1 (every block routed): the group has no dense members."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
        moe_every=1, num_experts=2, moe_top_k=2,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 8), 0, 32)
    params = model.init(jax.random.PRNGKey(7), tokens)["params"]
    dense = model.apply({"params": params}, tokens)
    pp = pipelined_lm_apply(model, params, tokens, stage_mesh)
    np.testing.assert_allclose(pp, dense, atol=1e-4, rtol=1e-4)


def test_pipelined_moe_lm_with_token_dropping_runs(stage_mesh):
    """top_k < num_experts (real routing with capacity drops): outputs
    are finite and shaped — exact whole-batch parity is impossible by
    design since routing is microbatch-local under pp."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    model = TransformerLM(
        vocab_size=64, d_model=32, num_heads=4, num_layers=8,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=64,
        moe_every=2, num_experts=4, moe_top_k=1,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(10), (8, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(11), tokens)["params"]
    pp = pipelined_lm_apply(model, params, tokens, stage_mesh)
    assert pp.shape == (8, 16, 64)
    assert bool(jnp.all(jnp.isfinite(pp)))


def test_pipelined_moe_lm_grads_match_dense(stage_mesh):
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=8,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
        moe_every=2, num_experts=2, moe_top_k=2,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(8), (4, 8), 0, 32)
    params = model.init(jax.random.PRNGKey(9), tokens)["params"]

    def dense_loss(p):
        return jnp.mean(model.apply({"params": p}, tokens) ** 2)

    def pp_loss(p):
        return jnp.mean(pipelined_lm_apply(model, p, tokens, stage_mesh) ** 2)

    g_dense = jax.grad(dense_loss)(params)
    g_pp = jax.grad(pp_loss)(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4),
        g_dense, g_pp,
    )


def test_pipelined_moe_aux_loss_matches_dense(stage_mesh):
    """The sown load-balancing loss rides the ring (round 3):
    mean-over-microbatches equals the dense whole-batch aux exactly in
    drop-free routing (density == 1 for every expert, and the per-
    microbatch mean-prob average telescopes to the whole-batch mean)."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    model = TransformerLM(
        vocab_size=64, d_model=32, num_heads=4, num_layers=8,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=64,
        moe_every=2, num_experts=4, moe_top_k=4,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(12), (8, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(13), tokens)["params"]

    _, mods = model.apply({"params": params}, tokens, mutable=["losses"])
    dense_aux = sum_sown_losses(mods)
    logits, pp_aux = pipelined_lm_apply(
        model, params, tokens, stage_mesh, return_aux=True)
    assert logits.shape == (8, 16, 64)
    np.testing.assert_allclose(float(pp_aux), float(dense_aux), rtol=1e-5)
    # aux participates in the pp backward like any loss term
    g = jax.grad(lambda p: pipelined_lm_apply(
        model, p, tokens, stage_mesh, return_aux=True)[1])(params)
    router_g = g["block_1"]["moe"]["router"]["kernel"]
    assert float(jnp.abs(router_g).max()) > 0


# -- inner-axis composition: sp and ep inside pp stages (round 3) ------------


def test_pp_with_sp_inside_stages_matches_dense():
    """mesh {stage: 2, seq: 2}: sequence shards ride inside each
    pipeline stage (ring_attention_local over the seq axis, RoPE offset
    by shard) and the seq-sharded logits match the dense apply."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    mesh = mesh_lib.make_mesh({"stage": 2, "seq": 2}, devices=jax.devices()[:4])
    model = TransformerLM(
        vocab_size=64, d_model=32, num_heads=4, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(20), (4, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(21), tokens)["params"]

    logits = jax.jit(
        lambda p, t: pipelined_lm_apply(model, p, t, mesh, seq_axis="seq")
    )(params, tokens)
    dense = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(logits, dense, atol=1e-4, rtol=1e-4)


def test_pp_with_ep_inside_stages_matches_dense():
    """mesh {stage: 2, expert: 2}: expert stacks shard over the inner
    axis (each device runs its local experts, psum combines) and both
    logits and the ring-carried aux match the dense apply."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    mesh = mesh_lib.make_mesh({"stage": 2, "expert": 2}, devices=jax.devices()[:4])
    model = TransformerLM(
        vocab_size=64, d_model=32, num_heads=4, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
        moe_every=2, num_experts=4, moe_top_k=4,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(22), (4, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(23), tokens)["params"]

    logits, pp_aux = jax.jit(
        lambda p, t: pipelined_lm_apply(
            model, p, t, mesh, expert_axis="expert", return_aux=True)
    )(params, tokens)
    dense, mods = model.apply({"params": params}, tokens, mutable=["losses"])
    np.testing.assert_allclose(logits, dense, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(float(pp_aux), float(sum_sown_losses(mods)), rtol=1e-5)


def test_pp_sp_moe_raises():
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    mesh = mesh_lib.make_mesh({"stage": 2, "seq": 2}, devices=jax.devices()[:4])
    model = TransformerLM(
        vocab_size=64, d_model=32, num_heads=4, num_layers=4,
        moe_every=2, attention_impl="reference",
    )
    tokens = jnp.zeros((4, 16), jnp.int32)
    with pytest.raises(NotImplementedError):
        pipelined_lm_apply(model, {}, tokens, mesh, seq_axis="seq")


def test_pp_train_step_matches_dense_train_step(stage_mesh):
    """One optimizer step through the ring equals one dense step: same
    loss, same updated params (logit parity extends to grads)."""
    import optax

    from hops_tpu.models import common
    from hops_tpu.models.transformer import TransformerLM, make_lm_train_step
    from hops_tpu.parallel.pipeline import make_pp_lm_train_step

    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(30), (4, 9), 0, 32)
    state = common.create_train_state(
        model, jax.random.PRNGKey(31), (4, 8),
        optimizer=optax.sgd(0.1), input_dtype=jnp.int32,
    )

    dense_state, dense_metrics = make_lm_train_step()(state, {"tokens": tokens})
    pp_state, pp_metrics = make_pp_lm_train_step(model, stage_mesh)(
        state, {"tokens": tokens})
    np.testing.assert_allclose(
        float(pp_metrics["loss"]), float(dense_metrics["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4),
        pp_state.params, dense_state.params,
    )


def test_pp_train_step_with_inner_sp():
    """Training through pp x sp: loss decreases over a few steps on the
    composed {stage, seq} mesh."""
    import optax

    from hops_tpu.models import common
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import make_pp_lm_train_step

    mesh = mesh_lib.make_mesh({"stage": 2, "seq": 2}, devices=jax.devices()[:4])
    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
    )
    state = common.create_train_state(
        model, jax.random.PRNGKey(32), (4, 8),
        optimizer=optax.adam(1e-2), input_dtype=jnp.int32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(33), (4, 9), 0, 32)
    step = jax.jit(make_pp_lm_train_step(model, mesh, seq_axis="seq"))
    losses = []
    for _ in range(5):
        state, metrics = step(state, {"tokens": tokens})
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_dp_outside_pp_matches_dense():
    """mesh {data: 2, stage: 2}: every data coordinate runs its own
    microbatch ring over its batch shard; logits match dense and one
    train step reproduces the dense update (grad summation over the
    data axis falls out of shard_map's transpose)."""
    import optax

    from hops_tpu.models import common
    from hops_tpu.models.transformer import TransformerLM, make_lm_train_step
    from hops_tpu.parallel.pipeline import make_pp_lm_train_step, pipelined_lm_apply

    mesh = mesh_lib.make_mesh({"data": 2, "stage": 2}, devices=jax.devices()[:4])
    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(40), (8, 9), 0, 32)
    params = model.init(jax.random.PRNGKey(41), tokens[:, :8])["params"]

    logits = jax.jit(
        lambda p, t: pipelined_lm_apply(model, p, t, mesh, batch_axis="data")
    )(params, tokens[:, :8])
    dense = model.apply({"params": params}, tokens[:, :8])
    np.testing.assert_allclose(logits, dense, atol=1e-4, rtol=1e-4)

    state = common.create_train_state(
        model, jax.random.PRNGKey(42), (8, 8),
        optimizer=optax.sgd(0.1), input_dtype=jnp.int32,
    )
    dense_state, dense_metrics = make_lm_train_step()(state, {"tokens": tokens})
    pp_state, pp_metrics = make_pp_lm_train_step(model, mesh, batch_axis="data")(
        state, {"tokens": tokens})
    np.testing.assert_allclose(
        float(pp_metrics["loss"]), float(dense_metrics["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4),
        pp_state.params, dense_state.params,
    )


def test_dp_pp_sp_three_axis_composition():
    """mesh {data: 2, stage: 2, seq: 2} — all three axes at once: dp
    outside the ring, sp inside the stages."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    mesh = mesh_lib.make_mesh(
        {"data": 2, "stage": 2, "seq": 2}, devices=jax.devices()[:8]
    )
    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(43), (4, 16), 0, 32)
    params = model.init(jax.random.PRNGKey(44), tokens)["params"]
    logits = jax.jit(
        lambda p, t: pipelined_lm_apply(
            model, p, t, mesh, batch_axis="data", seq_axis="seq")
    )(params, tokens)
    dense = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(logits, dense, atol=1e-4, rtol=1e-4)


def test_pp_with_tp_inside_stages_matches_dense():
    """mesh {stage: 2, model: 2}: Megatron split inside each stage —
    qkv/gate/up column-sharded, out/down row-sharded with psum — and
    the logits match the dense apply; one train step matches too."""
    import optax

    from hops_tpu.models import common
    from hops_tpu.models.transformer import TransformerLM, make_lm_train_step
    from hops_tpu.parallel.pipeline import make_pp_lm_train_step, pipelined_lm_apply

    mesh = mesh_lib.make_mesh({"stage": 2, "model": 2}, devices=jax.devices()[:4])
    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(50), (4, 9), 0, 32)
    params = model.init(jax.random.PRNGKey(51), tokens[:, :8])["params"]

    logits = jax.jit(
        lambda p, t: pipelined_lm_apply(model, p, t, mesh, tp_axis="model")
    )(params, tokens[:, :8])
    dense = model.apply({"params": params}, tokens[:, :8])
    np.testing.assert_allclose(logits, dense, atol=1e-4, rtol=1e-4)

    state = common.create_train_state(
        model, jax.random.PRNGKey(52), (4, 8),
        optimizer=optax.sgd(0.1), input_dtype=jnp.int32,
    )
    dense_state, dense_metrics = make_lm_train_step()(state, {"tokens": tokens})
    pp_state, pp_metrics = make_pp_lm_train_step(model, mesh, tp_axis="model")(
        state, {"tokens": tokens})
    np.testing.assert_allclose(
        float(pp_metrics["loss"]), float(dense_metrics["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4),
        pp_state.params, dense_state.params,
    )


def test_dp_pp_tp_three_axis_composition():
    """mesh {data: 2, stage: 2, model: 2} — classic 3D parallelism."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    mesh = mesh_lib.make_mesh(
        {"data": 2, "stage": 2, "model": 2}, devices=jax.devices()[:8]
    )
    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(53), (8, 8), 0, 32)
    params = model.init(jax.random.PRNGKey(54), tokens)["params"]
    logits = jax.jit(
        lambda p, t: pipelined_lm_apply(
            model, p, t, mesh, batch_axis="data", tp_axis="model")
    )(params, tokens)
    dense = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(logits, dense, atol=1e-4, rtol=1e-4)


def test_dp_pp_ep_three_axis_composition():
    """mesh {data: 2, stage: 2, expert: 2} — dp outside the ring with
    expert-sharded stacks inside; logits and the data-averaged aux
    match dense (regression: the aux carry wasn't marked data-varying)."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    mesh = mesh_lib.make_mesh(
        {"data": 2, "stage": 2, "expert": 2}, devices=jax.devices()[:8]
    )
    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
        moe_every=2, num_experts=2, moe_top_k=2,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(60), (8, 8), 0, 32)
    params = model.init(jax.random.PRNGKey(61), tokens)["params"]
    logits, aux = jax.jit(
        lambda p, t: pipelined_lm_apply(
            model, p, t, mesh, batch_axis="data", expert_axis="expert",
            return_aux=True)
    )(params, tokens)
    dense = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(logits, dense, atol=1e-4, rtol=1e-4)
    assert np.isfinite(float(aux))


def test_pp_with_gqa_model_matches_dense(stage_mesh):
    """A GQA TransformerLM (split q/kv projections) pipelines: the
    stage Block carries num_kv_heads so the param trees line up, and
    pp x tp shards the q/kv kernels (review regression)."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=4, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
        num_kv_heads=2,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(70), (4, 8), 0, 32)
    params = model.init(jax.random.PRNGKey(71), tokens)["params"]
    dense = model.apply({"params": params}, tokens)
    pp = pipelined_lm_apply(model, params, tokens, stage_mesh)
    np.testing.assert_allclose(pp, dense, atol=1e-4, rtol=1e-4)

    tp_mesh = mesh_lib.make_mesh({"stage": 2, "model": 2}, devices=jax.devices()[:4])
    pp_tp = jax.jit(
        lambda p, t: pipelined_lm_apply(model, p, t, tp_mesh, tp_axis="model")
    )(params, tokens)
    np.testing.assert_allclose(pp_tp, dense, atol=1e-4, rtol=1e-4)


def test_pp_windowed_lm_matches_dense(stage_mesh):
    """Advisor r3 (high): the stage Block must carry window=model.window,
    else a sliding-window LM silently computes full causal attention
    through the pipeline."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
        window=4,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(80), (4, 16), 0, 32)
    params = model.init(jax.random.PRNGKey(81), tokens)["params"]
    dense = model.apply({"params": params}, tokens)
    pp = pipelined_lm_apply(model, params, tokens, stage_mesh)
    np.testing.assert_allclose(pp, dense, atol=1e-4, rtol=1e-4)
    # Sanity: the window genuinely changes the logits at seq > window.
    full = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
    ).apply({"params": params}, tokens)
    assert not np.allclose(full, dense, atol=1e-3)


def test_pp_gqa_moe_lm_matches_dense(stage_mesh):
    """Advisor r3 (low): the stage MoEBlock must carry num_kv_heads —
    a GQA MoE model previously failed with ScopeParamNotFoundError
    when pipelined."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=4, num_layers=8,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
        num_kv_heads=2, moe_every=2, num_experts=2, moe_top_k=2,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(82), (4, 8), 0, 32)
    params = model.init(jax.random.PRNGKey(83), tokens)["params"]
    dense = model.apply({"params": params}, tokens)
    pp = pipelined_lm_apply(model, params, tokens, stage_mesh)
    np.testing.assert_allclose(pp, dense, atol=1e-4, rtol=1e-4)


def test_pp_windowed_moe_lm_matches_dense(stage_mesh):
    """Advisor r3 (medium): windowed MoE — the MoE layers' attention
    must honor the sliding window too, pipelined and dense alike."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=8,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
        window=4, moe_every=2, num_experts=2, moe_top_k=2,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(84), (4, 16), 0, 32)
    params = model.init(jax.random.PRNGKey(85), tokens)["params"]
    dense = model.apply({"params": params}, tokens)
    pp = pipelined_lm_apply(model, params, tokens, stage_mesh)
    np.testing.assert_allclose(pp, dense, atol=1e-4, rtol=1e-4)


# -- explicit schedules: gpipe / 1F1B / interleaved ---------------------------


def _sched_lm_and_state():
    import optax

    from hops_tpu.models import common
    from hops_tpu.models.transformer import TransformerLM

    model = TransformerLM(
        vocab_size=32, d_model=16, num_heads=2, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
    )
    state = common.create_train_state(
        model, jax.random.PRNGKey(91), (4, 8),
        optimizer=optax.sgd(0.1), input_dtype=jnp.int32,
    )
    tokens = {"tokens": jax.random.randint(jax.random.PRNGKey(92), (8, 9), 0, 32)}
    return model, state, tokens


def test_all_schedules_bit_identical_losses_and_grads():
    """The tentpole equivalence matrix: 1F1B and interleaved produce
    bit-identical losses to the sequential (gpipe) schedule; gradients
    (observed through the SGD update) are bit-identical at matched
    parameter chunking — gpipe-vs-1f1b at v=1, gpipe-vs-interleaved at
    v=2 (re-blocking layers into different scan chunks legitimately
    perturbs single ULPs, so the sequential reference uses the same
    chunking; losses are forward-only and match across v too)."""
    from hops_tpu.parallel.pipeline import make_pp_lm_train_step

    model, state, tokens = _sched_lm_and_state()
    mesh = mesh_lib.make_mesh({"stage": 2}, devices=jax.devices()[:2])
    out = {}
    for name, kind, v in [
        ("gpipe", "gpipe", 1), ("1f1b", "1f1b", 1),
        ("gpipe_v2", "gpipe", 2), ("interleaved", "interleaved", 2),
    ]:
        step = jax.jit(make_pp_lm_train_step(
            model, mesh, schedule=kind, num_microbatches=4, virtual_stages=v))
        st, metrics = step(state, tokens)
        out[name] = (st, float(metrics["loss"]))
    # Losses: bit-identical across ALL schedules and chunkings.
    assert len({loss for _, loss in out.values()}) == 1
    # Gradients: bit-identical at matched chunking.
    for a, b in [("gpipe", "1f1b"), ("gpipe_v2", "interleaved")]:
        for x, y in zip(jax.tree.leaves(out[a][0].params),
                        jax.tree.leaves(out[b][0].params)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    # And across chunkings the update still agrees to float tolerance.
    for x, y in zip(jax.tree.leaves(out["gpipe"][0].params),
                    jax.tree.leaves(out["interleaved"][0].params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)


def test_scheduled_gpipe_matches_autodiff_ring_and_dense():
    """The explicit tick program is a different derivation of the same
    math: its loss/update agree with the legacy autodiff fill-drain
    ring AND the dense (unpipelined) train step to float tolerance."""
    from hops_tpu.models.transformer import make_lm_train_step
    from hops_tpu.parallel.pipeline import make_pp_lm_train_step

    model, state, tokens = _sched_lm_and_state()
    mesh = mesh_lib.make_mesh({"stage": 2}, devices=jax.devices()[:2])
    exp_state, exp_metrics = jax.jit(make_pp_lm_train_step(
        model, mesh, schedule="gpipe", num_microbatches=4))(state, tokens)
    ring_state, ring_metrics = make_pp_lm_train_step(
        model, mesh, num_microbatches=4)(state, tokens)
    dense_state, dense_metrics = make_lm_train_step()(state, tokens)
    np.testing.assert_allclose(
        float(exp_metrics["loss"]), float(dense_metrics["loss"]), rtol=1e-5)
    np.testing.assert_allclose(
        float(exp_metrics["loss"]), float(ring_metrics["loss"]), rtol=1e-5)
    for x, y in zip(jax.tree.leaves(exp_state.params),
                    jax.tree.leaves(dense_state.params)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=1e-4, rtol=1e-4)


def test_schedule_telemetry_bubble_ordering():
    """Bubble gauges are registered for every built schedule and the
    interleaved schedule's bubble beats sequential at equal m."""
    from hops_tpu.parallel.pipeline import make_pp_lm_train_step
    from hops_tpu.telemetry import REGISTRY

    model, _, _ = _sched_lm_and_state()
    mesh = mesh_lib.make_mesh({"stage": 2}, devices=jax.devices()[:2])
    scheds = {}
    for kind in ("gpipe", "1f1b", "interleaved"):
        step = make_pp_lm_train_step(
            model, mesh, schedule=kind, num_microbatches=4)
        scheds[kind] = step.pp_schedule
    gauge = REGISTRY.gauge("hops_tpu_pp_bubble_fraction", labels=("schedule",))
    for kind, sched in scheds.items():
        assert gauge.value(schedule=kind) == pytest.approx(sched.bubble_fraction)
    assert scheds["interleaved"].bubble_fraction < scheds["gpipe"].bubble_fraction
    assert scheds["1f1b"].peak_in_flight <= mesh.shape["stage"]


def test_pp_sp_gqa_windowed_matches_dense():
    """Composition stack: GQA + sliding window + sequence parallelism
    INSIDE pipeline stages — the ring_attention_local body folds
    un-repeated kv-head groups per shard and still honors the window."""
    from hops_tpu.models.transformer import TransformerLM
    from hops_tpu.parallel.pipeline import pipelined_lm_apply

    mesh = mesh_lib.make_mesh({"stage": 2, "seq": 2}, devices=jax.devices()[:4])
    model = TransformerLM(
        vocab_size=64, d_model=32, num_heads=4, num_layers=4,
        dtype=jnp.float32, attention_impl="reference", max_decode_len=32,
        num_kv_heads=2, window=4,
    )
    tokens = jax.random.randint(jax.random.PRNGKey(30), (4, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(31), tokens)["params"]
    logits = jax.jit(
        lambda p, t: pipelined_lm_apply(model, p, t, mesh, seq_axis="seq")
    )(params, tokens)
    dense = model.apply({"params": params}, tokens)
    np.testing.assert_allclose(logits, dense, atol=1e-4, rtol=1e-4)
