"""Pipeline parallelism: output and gradient parity with sequential."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hops_tpu.parallel import mesh as mesh_lib
from hops_tpu.parallel.pipeline import pipeline_apply, stack_stage_params

STAGES = 4
DIM = 16


def _stage_params(seed):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    return {
        "w": jax.random.normal(k1, (DIM, DIM)) * 0.3,
        "b": jax.random.normal(k2, (DIM,)) * 0.1,
    }


def stage_fn(params, h):
    return h + jnp.tanh(h @ params["w"] + params["b"])  # residual, shape-preserving


def _sequential(stages, x):
    for p in stages:
        x = stage_fn(p, x)
    return x


@pytest.fixture(scope="module")
def stage_mesh():
    return mesh_lib.make_mesh({"stage": STAGES}, devices=jax.devices()[:STAGES])


def test_pipeline_matches_sequential(stage_mesh):
    stages = [_stage_params(i) for i in range(STAGES)]
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(9), (8, DIM))
    out = pipeline_apply(stage_fn, stacked, x, stage_mesh)
    np.testing.assert_allclose(out, _sequential(stages, x), atol=1e-5, rtol=1e-5)


def test_pipeline_more_microbatches(stage_mesh):
    stages = [_stage_params(i) for i in range(STAGES)]
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(3), (16, DIM))
    out = pipeline_apply(stage_fn, stacked, x, stage_mesh, num_microbatches=8)
    np.testing.assert_allclose(out, _sequential(stages, x), atol=1e-5, rtol=1e-5)


def test_pipeline_grads_match(stage_mesh):
    stages = [_stage_params(i) for i in range(STAGES)]
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.PRNGKey(5), (8, DIM))

    def pp_loss(stacked):
        return pipeline_apply(stage_fn, stacked, x, stage_mesh).sum()

    def seq_loss(stacked):
        stages = [jax.tree.map(lambda p: p[i], stacked) for i in range(STAGES)]
        return _sequential(stages, x).sum()

    g_pp = jax.grad(pp_loss)(stacked)
    g_seq = jax.grad(seq_loss)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4), g_pp, g_seq
    )


def test_pipeline_rejects_bad_microbatch(stage_mesh):
    stacked = stack_stage_params([_stage_params(i) for i in range(STAGES)])
    x = jnp.zeros((6, DIM))
    with pytest.raises(ValueError, match="microbatches"):
        pipeline_apply(stage_fn, stacked, x, stage_mesh)
