"""Embedded search index (the hops.elasticsearch twin)."""

from hops_tpu import experiment
from hops_tpu.messaging import searchindex


def test_index_and_search_ranking():
    idx = searchindex.SearchIndex("docs")
    idx.index_document("a", {"title": "resnet training run", "status": "FINISHED"})
    idx.index_document("b", {"title": "mnist training run", "status": "FAILED"})
    idx.index_document("c", {"title": "data validation", "status": "FINISHED"})
    hits = idx.search("training run finished")
    assert hits[0]["_id"] == "a"  # matches all three terms
    assert {h["_id"] for h in hits} == {"a", "b", "c"}
    assert idx.count() == 3


def test_last_write_wins():
    idx = searchindex.SearchIndex("upserts")
    idx.index_document("x", {"v": 1})
    idx.index_document("x", {"v": 2})
    assert idx.get("x") == {"v": 2}
    assert idx.count() == 1


def test_runs_indexed_by_experiment_launch():
    experiment.launch(lambda: {"accuracy": 0.9}, name="searchable_run")
    hits = searchindex.search_runs("searchable_run finished")
    assert hits and hits[0]["_source"]["name"] == "searchable_run"


def test_es_config_shape():
    cfg = searchindex.get_elasticsearch_config("logs")
    assert cfg["es.resource"].endswith("_logs/_doc")
    assert "es.nodes" in cfg
