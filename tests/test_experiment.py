"""Launcher tests: the reference's UX contract on the fake mesh.

Golden-record style mirrors SURVEY.md §4.1 — returned (path, metrics)
tuples are the observable contract.
"""

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from hops_tpu import experiment
from hops_tpu.experiment import registry, tensorboard
from hops_tpu.parallel import get_strategy


class TestLaunch:
    def test_launch_returns_path_and_metrics(self):
        def train_fn():
            print("hello from wrapper")
            tensorboard.scalar(0, "loss", 1.0)
            return {"accuracy": 0.92}

        path, metrics = experiment.launch(train_fn, name="mnist", metric_key="accuracy")
        assert "Experiments" in path
        assert metrics["accuracy"] == 0.92
        assert metrics["metric"] == 0.92
        # output.log captured user stdout
        assert "hello from wrapper" in Path(metrics["log"]).read_text()
        # metrics.jsonl written via tensorboard facade
        events = (Path(path) / "metrics.jsonl").read_text()
        assert json.loads(events.splitlines()[0])["tag"] == "loss"

    def test_launch_with_args(self):
        def train_fn(lr, steps):
            return {"lr_used": lr, "steps": steps}

        _, metrics = experiment.launch(train_fn, args={"lr": 0.1, "steps": 5})
        assert metrics["lr_used"] == 0.1

    def test_scalar_return_becomes_metric(self):
        _, metrics = experiment.launch(lambda: 0.5)
        assert metrics["metric"] == 0.5

    def test_registry_records_run(self):
        experiment.launch(lambda: {"m": 1.0}, name="reg-test", metric_key="m")
        runs = registry.list_runs("reg-test")
        assert len(runs) == 1
        assert runs[0]["status"] == "FINISHED"
        assert runs[0]["metrics"]["m"] == 1.0

    def test_failure_registered_and_reraised(self):
        def bad():
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError):
            experiment.launch(bad, name="fail-test")
        runs = registry.list_runs("fail-test")
        assert runs[0]["status"] == "FAILED"

    def test_best_run(self):
        experiment.launch(lambda: {"acc": 0.5}, name="best", metric_key="acc")
        experiment.launch(lambda: {"acc": 0.9}, name="best", metric_key="acc")
        best = registry.best_run("best", metric="acc")
        assert best["metrics"]["acc"] == 0.9
        worst = registry.best_run("best", metric="acc", direction="min")
        assert worst["metrics"]["acc"] == 0.5


class TestDistributedLaunchers:
    def test_mirrored_exposes_strategy(self):
        def train_fn():
            s = get_strategy()
            return {"replicas": s.num_replicas_in_sync}

        _, metrics = experiment.mirrored(train_fn, name="mir")
        assert metrics["replicas"] == 8

    def test_collective_all_reduce_trains(self):
        """End-to-end: data-parallel training of a tiny linear model over
        the 8-device mesh inside the launcher."""

        def train_fn():
            s = get_strategy()
            w = s.replicate(jnp.zeros((4,)))
            import numpy as np

            rs = np.random.RandomState(0)
            x = rs.randn(64, 4).astype("float32")
            true_w = np.array([1.0, -2.0, 3.0, 0.5], "float32")
            y = x @ true_w

            def step(w, batch):
                def loss(w):
                    return jnp.mean((batch["x"] @ w - batch["y"]) ** 2)

                return w - 0.1 * jax.grad(loss)(w), {"loss": loss(w)}

            compiled = s.step(step, donate_state=False)
            for _ in range(100):
                w, m = compiled(w, s.distribute_batch({"x": x, "y": y}))
            return {"final_loss": float(m["loss"])}

        _, metrics = experiment.collective_all_reduce(train_fn, name="car")
        assert metrics["final_loss"] < 1e-3

    def test_parameter_server_alias(self):
        _, metrics = experiment.parameter_server(lambda: {"ok": 1.0}, name="ps")
        assert metrics["ok"] == 1.0
        assert registry.list_runs("ps")[0]["kind"] == "collective_all_reduce"
