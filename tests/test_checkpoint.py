"""Checkpoint/resume + diagnostics subsystems."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from hops_tpu.models import common
from hops_tpu.models.mnist import FFN
from hops_tpu.parallel import mesh as mesh_lib
from hops_tpu.runtime import checkpoint, diagnostics


def _state():
    return common.create_train_state(
        FFN(dtype=jnp.float32), jax.random.PRNGKey(0), (2, 28, 28, 1)
    )


@pytest.mark.slow  # heavy jit compile (fast-tier budget: round-5 re-tiering)
def test_save_restore_roundtrip(tmp_path):
    state = _state()
    with checkpoint.CheckpointManager(tmp_path / "ckpt", async_save=False) as mgr:
        assert mgr.save(0, state)
        restored = mgr.restore(state)
    jax.tree.map(np.testing.assert_allclose, restored.params, state.params)
    assert int(restored.step) == int(state.step)


def test_max_to_keep_and_latest(tmp_path):
    state = _state()
    with checkpoint.CheckpointManager(tmp_path / "c", max_to_keep=2, async_save=False) as m:
        for s in (0, 1, 2, 3):
            m.save(s, state)
        assert m.latest_step() == 3
        assert m.all_steps() == [2, 3]


def test_restore_or_init_fresh_and_resume(tmp_path):
    state = _state()
    out, start = checkpoint.restore_or_init(state, tmp_path / "r")
    assert start == 0 and out is state
    with checkpoint.CheckpointManager(tmp_path / "r", async_save=False) as m:
        m.save(7, state)
    _, start = checkpoint.restore_or_init(state, tmp_path / "r")
    assert start == 8


def test_async_save_visible_after_wait(tmp_path):
    state = _state()
    with checkpoint.CheckpointManager(tmp_path / "a", async_save=True) as m:
        m.save(0, state)
        m.wait()
        assert m.latest_step() == 0


def test_restore_onto_sharded_template(tmp_path):
    mesh = mesh_lib.make_mesh({"data": 4}, devices=jax.devices()[:4])
    state = _state()
    with checkpoint.CheckpointManager(tmp_path / "s", async_save=False) as m:
        m.save(0, state)
        sharded = jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P())), state
        )
        restored = m.restore(sharded)
    leaf = restored.params["Dense_0"]["kernel"]
    assert leaf.sharding == NamedSharding(mesh, P())


def test_watchdog_fires_on_stall():
    fired = threading.Event()
    wd = diagnostics.Watchdog(timeout_s=0.3, on_hang=fired.set)
    with wd:
        time.sleep(1.0)
    assert wd.fired and fired.is_set()


def test_watchdog_quiet_with_heartbeats():
    wd = diagnostics.Watchdog(timeout_s=0.6)
    with wd:
        for _ in range(5):
            time.sleep(0.1)
            wd.heartbeat()
    assert not wd.fired


def test_deterministic_mode_reproduces():
    with diagnostics.deterministic_mode(42) as key1:
        a = jax.random.normal(key1, (8,))
    with diagnostics.deterministic_mode(42) as key2:
        b = jax.random.normal(key2, (8,))
    np.testing.assert_array_equal(a, b)


def test_trace_writes_into_rundir(tmp_path):
    with diagnostics.trace(str(tmp_path / "tr")) as target:
        jnp.ones((4, 4)).sum().block_until_ready()
    import os

    assert os.listdir(target)


# -- preemption-safe training (runtime/preemption.py) ------------------------


def test_preemption_guard_catches_sigterm():
    import os
    import signal

    from hops_tpu.runtime.preemption import PreemptionGuard

    with PreemptionGuard() as guard:
        assert not guard.should_stop()
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)
        assert guard.should_stop()
    # Uninstalled: default disposition restored.
    assert signal.getsignal(signal.SIGTERM) != guard._handler


def test_preemption_guard_chains_previous_handler():
    import os
    import signal

    from hops_tpu.runtime.preemption import PreemptionGuard

    seen = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        with PreemptionGuard() as guard:
            os.kill(os.getpid(), signal.SIGTERM)
            time.sleep(0.05)
            assert guard.should_stop() and seen == [signal.SIGTERM]
        assert signal.getsignal(signal.SIGTERM) is not None
    finally:
        signal.signal(signal.SIGTERM, prev)


@pytest.mark.slow  # heavy jit compile (fast-tier budget: round-5 re-tiering)
def test_run_preemptible_checkpoints_and_resumes(tmp_path):
    """Preemption mid-run saves at the step boundary and exits; a second
    incarnation resumes from there and finishes the epoch."""
    from hops_tpu.runtime.preemption import PreemptionGuard, run_preemptible

    step_fn = jax.jit(common.make_train_step())
    rs = np.random.RandomState(0)
    batches = [
        {"image": rs.rand(2, 28, 28, 1).astype(np.float32),
         "label": rs.randint(0, 10, 2)}
        for _ in range(6)
    ]

    guard = PreemptionGuard(install=False)
    calls = []

    def preempting_step(state, batch):
        calls.append(1)
        if len(calls) == 3:
            guard.notice()  # delivered "mid-step"; honored at the boundary
        return step_fn(state, batch)

    state, metrics, done = run_preemptible(
        preempting_step, _state(), batches,
        directory=str(tmp_path / "ck"), save_every=100, guard=guard)
    assert done == 3 and len(calls) == 3
    assert np.isfinite(float(metrics["loss"]))
    with checkpoint.CheckpointManager(tmp_path / "ck", async_save=False) as mgr:
        assert mgr.latest_step() == 2  # the boundary it was preempted at

    # Second incarnation: skips steps 0-2, finishes 3-5.
    state2, metrics2, done2 = run_preemptible(
        step_fn, _state(), batches, directory=str(tmp_path / "ck"),
        save_every=100, guard=PreemptionGuard(install=False))
    assert done2 == 6
    assert int(state2.step) == 6  # 3 restored + 3 new optimizer steps


def test_run_preemptible_preempt_on_interval_step(tmp_path):
    """Review regression: preemption landing on a step the interval save
    just wrote must not re-save (orbax raises StepAlreadyExistsError on
    overwrite, even with force=True)."""
    from hops_tpu.runtime.preemption import PreemptionGuard, run_preemptible

    step_fn = jax.jit(common.make_train_step())
    rs = np.random.RandomState(0)
    batches = [
        {"image": rs.rand(2, 28, 28, 1).astype(np.float32),
         "label": rs.randint(0, 10, 2)}
        for _ in range(4)
    ]
    guard = PreemptionGuard(install=False)

    def step_then_preempt(state, batch):
        guard.notice()  # every step coincides with save_every=1
        return step_fn(state, batch)

    state, _, done = run_preemptible(
        step_then_preempt, _state(), batches,
        directory=str(tmp_path / "ck"), save_every=1, guard=guard)
    assert done == 1  # stopped at the first boundary, no crash


def test_run_preemptible_final_state_is_durable(tmp_path):
    """Review regression: normal completion checkpoints the last step
    even when it falls between save_every intervals."""
    from hops_tpu.runtime.preemption import PreemptionGuard, run_preemptible

    step_fn = jax.jit(common.make_train_step())
    rs = np.random.RandomState(0)
    batches = [
        {"image": rs.rand(2, 28, 28, 1).astype(np.float32),
         "label": rs.randint(0, 10, 2)}
        for _ in range(5)
    ]
    run_preemptible(step_fn, _state(), batches,
                    directory=str(tmp_path / "ck"), save_every=100,
                    guard=PreemptionGuard(install=False))
    with checkpoint.CheckpointManager(tmp_path / "ck", async_save=False) as mgr:
        assert mgr.latest_step() == 4


def test_preemption_guard_install_is_idempotent():
    import os
    import signal

    from hops_tpu.runtime.preemption import PreemptionGuard

    guard = PreemptionGuard()
    try:
        guard.install()  # second install must not chain to itself
        os.kill(os.getpid(), signal.SIGTERM)  # would recurse before the fix
        time.sleep(0.05)
        assert guard.should_stop()
    finally:
        guard.uninstall()
    assert signal.getsignal(signal.SIGTERM) != guard._handler


def test_restore_onto_smaller_mesh(tmp_path):
    """Elastic resume: a state saved sharded over 8 devices restores
    onto a 4-device mesh (the docstring's 'resume on a differently-sized
    slice' promise, now proven)."""
    from jax.sharding import NamedSharding

    mesh8 = mesh_lib.make_mesh({"data": 8})
    state = _state()
    state8 = jax.device_put(state, NamedSharding(mesh8, P()))
    with checkpoint.CheckpointManager(tmp_path / "ck", async_save=False) as mgr:
        mgr.save(0, state8)

    mesh4 = mesh_lib.make_mesh({"data": 4}, devices=jax.devices()[:4])
    template = jax.device_put(state, NamedSharding(mesh4, P()))
    with checkpoint.CheckpointManager(tmp_path / "ck", async_save=False) as mgr:
        restored = mgr.restore(template)
    leaf = jax.tree.leaves(restored.params)[0]
    assert set(leaf.sharding.device_set) == set(jax.devices()[:4])
    jax.tree.map(np.testing.assert_allclose, restored.params, state.params)


@pytest.mark.slow  # heavy jit compile (fast-tier budget: round-5 re-tiering)
def test_run_preemptible_callable_batches_fast_forward(tmp_path):
    """batches may be callable(start_step) -> iterable: the resumed
    incarnation's stream starts AT the restored step (no draw-and-
    discard), and results match the plain-iterable path exactly."""
    from hops_tpu.runtime.preemption import PreemptionGuard, run_preemptible

    step_fn = jax.jit(common.make_train_step())
    rs = np.random.RandomState(0)
    all_batches = [
        {"image": rs.rand(2, 28, 28, 1).astype(np.float32),
         "label": rs.randint(0, 10, 2)}
        for _ in range(6)
    ]
    requested = []

    def make_stream(start):
        requested.append(start)
        return all_batches[start:]

    guard = PreemptionGuard(install=False)
    calls = []

    def preempting_step(state, batch):
        calls.append(1)
        if len(calls) == 3:
            guard.notice()
        return step_fn(state, batch)

    run_preemptible(preempting_step, _state(), make_stream,
                    directory=str(tmp_path / "ck"), save_every=100, guard=guard)
    state2, _, done2 = run_preemptible(
        step_fn, _state(), make_stream, directory=str(tmp_path / "ck"),
        save_every=100, guard=PreemptionGuard(install=False))
    assert requested == [0, 3]  # second stream born fast-forwarded
    assert done2 == 6 and int(state2.step) == 6
