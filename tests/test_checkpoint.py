"""Checkpoint/resume + diagnostics subsystems."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from hops_tpu.models import common
from hops_tpu.models.mnist import FFN
from hops_tpu.parallel import mesh as mesh_lib
from hops_tpu.runtime import checkpoint, diagnostics


def _state():
    return common.create_train_state(
        FFN(dtype=jnp.float32), jax.random.PRNGKey(0), (2, 28, 28, 1)
    )


def test_save_restore_roundtrip(tmp_path):
    state = _state()
    with checkpoint.CheckpointManager(tmp_path / "ckpt", async_save=False) as mgr:
        assert mgr.save(0, state)
        restored = mgr.restore(state)
    jax.tree.map(np.testing.assert_allclose, restored.params, state.params)
    assert int(restored.step) == int(state.step)


def test_max_to_keep_and_latest(tmp_path):
    state = _state()
    with checkpoint.CheckpointManager(tmp_path / "c", max_to_keep=2, async_save=False) as m:
        for s in (0, 1, 2, 3):
            m.save(s, state)
        assert m.latest_step() == 3
        assert m.all_steps() == [2, 3]


def test_restore_or_init_fresh_and_resume(tmp_path):
    state = _state()
    out, start = checkpoint.restore_or_init(state, tmp_path / "r")
    assert start == 0 and out is state
    with checkpoint.CheckpointManager(tmp_path / "r", async_save=False) as m:
        m.save(7, state)
    _, start = checkpoint.restore_or_init(state, tmp_path / "r")
    assert start == 8


def test_async_save_visible_after_wait(tmp_path):
    state = _state()
    with checkpoint.CheckpointManager(tmp_path / "a", async_save=True) as m:
        m.save(0, state)
        m.wait()
        assert m.latest_step() == 0


def test_restore_onto_sharded_template(tmp_path):
    mesh = mesh_lib.make_mesh({"data": 4}, devices=jax.devices()[:4])
    state = _state()
    with checkpoint.CheckpointManager(tmp_path / "s", async_save=False) as m:
        m.save(0, state)
        sharded = jax.tree.map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P())), state
        )
        restored = m.restore(sharded)
    leaf = restored.params["Dense_0"]["kernel"]
    assert leaf.sharding == NamedSharding(mesh, P())


def test_watchdog_fires_on_stall():
    fired = threading.Event()
    wd = diagnostics.Watchdog(timeout_s=0.3, on_hang=fired.set)
    with wd:
        time.sleep(1.0)
    assert wd.fired and fired.is_set()


def test_watchdog_quiet_with_heartbeats():
    wd = diagnostics.Watchdog(timeout_s=0.6)
    with wd:
        for _ in range(5):
            time.sleep(0.1)
            wd.heartbeat()
    assert not wd.fired


def test_deterministic_mode_reproduces():
    with diagnostics.deterministic_mode(42) as key1:
        a = jax.random.normal(key1, (8,))
    with diagnostics.deterministic_mode(42) as key2:
        b = jax.random.normal(key2, (8,))
    np.testing.assert_array_equal(a, b)


def test_trace_writes_into_rundir(tmp_path):
    with diagnostics.trace(str(tmp_path / "tr")) as target:
        jnp.ones((4, 4)).sum().block_until_ready()
    import os

    assert os.listdir(target)
