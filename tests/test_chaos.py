"""End-to-end chaos: inject the faults, survive them, prove the books.

The acceptance scenario from the resilience PR: with ``HOPS_TPU_FAULTS``
injecting a corrupt latest checkpoint step, a transient loader read
error, and serving handler faults, the platform finishes with the SAME
final state a fault-free run produces — recoveries visible on
``hops_tpu_run_recoveries_total``, the corrupt step quarantined, and
serving shedding overload with 503 + ``Retry-After`` while ``/healthz``
tracks the breaker. All state here is plain numpy (no jit compile), so
the chaos paths stay in the fast tier.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from hops_tpu.featurestore.loader import ArraySource, DataLoader
from hops_tpu.runtime import faultinject
from hops_tpu.runtime.preemption import PreemptionGuard, run_preemptible
from hops_tpu.runtime.resilience import RetryPolicy
from hops_tpu.telemetry.metrics import REGISTRY


@pytest.fixture(autouse=True)
def _disarmed():
    faultinject.disarm()
    yield
    faultinject.disarm()


def _counter(name: str, **labels) -> float:
    metric = REGISTRY.get(name)
    if metric is None:
        return 0.0
    try:
        return metric.value(**labels)
    except Exception:  # label child not created yet
        return 0.0


# -- the training-loop chaos scenario -----------------------------------------


def _train_step(state, batch):
    # n stays a 0-d ndarray (np scalar types are not checkpointable).
    return (
        {"w": state["w"] + batch["x"].sum(axis=0),
         "n": np.asarray(state["n"] + 1)},
        {"loss": float(np.sum(state["w"]))},
    )


def _fresh_state():
    return {"w": np.zeros(4, np.float64), "n": np.asarray(0)}


def _loader(n: int = 32, batch: int = 4) -> DataLoader:
    rs = np.random.RandomState(0)
    return DataLoader(
        ArraySource({"x": rs.rand(n, 4)}),
        batch,
        num_epochs=1,
        shuffle=False,
        num_workers=0,
        name="chaos",
    )


class TestTrainingChaos:
    def test_faulted_run_matches_fault_free_run(self, tmp_path, monkeypatch):
        """The headline: corrupt latest checkpoint + transient loader
        read error; the supervised run recovers (quarantine + fallback
        + replay) and lands on the byte-identical final state."""
        # Reference: no faults.
        ref_state, ref_metrics, ref_done = run_preemptible(
            _train_step, _fresh_state(), _loader(),
            directory=str(tmp_path / "ref"), save_every=3,
            guard=PreemptionGuard(install=False))
        assert ref_done == 8

        # Chaos: armed from the environment, exactly as an e2e harness
        # would do it. The loader read fails once at step 5; the
        # recovery's restore finds its newest step (3) corrupted at
        # rest, quarantines it, falls back to step 0, replays.
        monkeypatch.setenv(
            faultinject.ENV_VAR,
            "checkpoint.restore=corrupt@times=1;"
            "loader.read=error:OSError@times=1,after=5",
        )
        faultinject.arm_from_env()
        recoveries0 = _counter("hops_tpu_run_recoveries_total",
                               loop="preemptible")
        quarantined0 = _counter("hops_tpu_checkpoint_quarantined_total")
        try:
            state, metrics, done = run_preemptible(
                _train_step, _fresh_state(), _loader(),
                directory=str(tmp_path / "chaos"), save_every=3,
                max_recoveries=3,
                recovery_policy=RetryPolicy(base_delay_s=0.01, seed=0),
                guard=PreemptionGuard(install=False))
        finally:
            faultinject.disarm()

        assert done == ref_done == 8
        assert int(state["n"]) == int(ref_state["n"]) == 8
        np.testing.assert_array_equal(state["w"], ref_state["w"])
        assert metrics["loss"] == ref_metrics["loss"]
        # The books: one recovery, one quarantined step, visible.
        assert _counter("hops_tpu_run_recoveries_total",
                        loop="preemptible") == recoveries0 + 1
        assert _counter("hops_tpu_checkpoint_quarantined_total") \
            == quarantined0 + 1
        assert list((tmp_path / "chaos").glob("corrupt_*.quarantined"))

    def test_corrupt_save_detected_on_next_restore(self, tmp_path):
        """checkpoint.save=corrupt is post-manifest bitrot: the write
        looks clean, the NEXT incarnation's restore catches it."""
        faultinject.arm("checkpoint.save=corrupt@times=1,after=1")
        run_preemptible(
            _train_step, _fresh_state(), _loader(),
            directory=str(tmp_path / "ck"), save_every=3,
            guard=PreemptionGuard(install=False))
        faultinject.disarm()
        # Saves landed at steps 0, 3, 6, 7; passage 1 (step 3) was
        # corrupted after its manifest. Its verification must fail and
        # an explicit restore of it must refuse.
        from hops_tpu.runtime.checkpoint import (
            CheckpointCorruptError,
            CheckpointManager,
        )

        with CheckpointManager(tmp_path / "ck", async_save=False) as m:
            assert m.verify_step(3) is not None
            with pytest.raises(CheckpointCorruptError):
                m.restore(_fresh_state(), step=3)
            # Auto-restore is unaffected: newest step (7) is healthy.
            assert int(m.restore(_fresh_state())["n"]) == 8

    def test_resume_after_corrupt_latest_step_regression(self, tmp_path):
        """Satellite regression: NO supervisor — a preempted run whose
        latest checkpoint rots on disk must still resume (from the
        previous valid step) and finish with the right final state."""
        guard = PreemptionGuard(install=False)
        calls = []

        def preempting_step(state, batch):
            calls.append(1)
            if len(calls) == 5:
                guard.notice()  # stop at step-4 boundary
            return _train_step(state, batch)

        d = tmp_path / "ck"
        _, _, done = run_preemptible(
            preempting_step, _fresh_state(), _loader(),
            directory=str(d), save_every=3, guard=guard)
        assert done == 5  # steps 0-4; checkpoints at 0, 3, and forced 4
        faultinject.corrupt_directory(d / "4")

        state2, _, done2 = run_preemptible(
            _train_step, _fresh_state(), _loader(),
            directory=str(d), save_every=3,
            guard=PreemptionGuard(install=False))
        # Step 4 quarantined -> resumed from 3 -> replayed 4..7.
        assert done2 == 8 and int(state2["n"]) == 8
        ref, _, _ = run_preemptible(
            _train_step, _fresh_state(), _loader(),
            directory=str(tmp_path / "ref"), save_every=3,
            guard=PreemptionGuard(install=False))
        np.testing.assert_array_equal(state2["w"], ref["w"])

    def test_recoveries_exhausted_reraises(self, tmp_path):
        faultinject.arm("loader.read=error:OSError")  # every read fails
        with pytest.raises(OSError):
            run_preemptible(
                _train_step, _fresh_state(), _loader(),
                directory=str(tmp_path / "ck"), save_every=3,
                max_recoveries=2,
                recovery_policy=RetryPolicy(base_delay_s=0.001, seed=0),
                guard=PreemptionGuard(install=False))


# -- serving chaos -------------------------------------------------------------


def _post(port: int, name: str, body: dict, timeout: float = 10.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{name}:predict",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _healthz(port: int):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10
        ) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestServingChaos:
    def _start(self, tmp_path, name: str, rcfg: dict) -> int:
        from hops_tpu.modelrepo import serving

        script = tmp_path / "p.py"
        script.write_text(
            "class Predict:\n"
            "    def predict(self, instances):\n"
            "        return instances\n"
        )
        serving.create_or_update(
            name, model_path=str(tmp_path), model_server="PYTHON",
            resilience_config=rcfg)
        serving.start(name)
        return serving._load_registry()[name]["port"]

    def test_injected_overload_sheds_503_with_retry_after(self, tmp_path):
        from hops_tpu.modelrepo import serving

        port = self._start(tmp_path, "chaos-shed", {"max_inflight": 1})
        try:
            # Injected latency parks the only admitted request inside
            # the handler; the concurrent one must be shed, not queued.
            faultinject.arm("serving.handle=latency:0.4@times=1")
            results = []

            def bg():
                results.append(_post(port, "chaos-shed", {"instances": [[1]]}))

            t = threading.Thread(target=bg)
            t.start()
            time.sleep(0.15)  # let the slow request occupy the slot
            code, body, headers = _post(port, "chaos-shed",
                                        {"instances": [[2]]})
            t.join()
            assert code == 503 and "Retry-After" in headers
            assert results[0][0] == 200  # the slow one still succeeded
            assert _counter("hops_tpu_serving_shed_total",
                            model="chaos-shed", reason="overload") >= 1
            # Back under capacity: served again immediately.
            assert _post(port, "chaos-shed", {"instances": [[3]]})[0] == 200
        finally:
            serving.stop("chaos-shed")

    def test_deadline_zombie_still_holds_inflight_slot(self, tmp_path):
        """A 504'd request's abandoned predict keeps occupying its
        max_inflight slot until the computation actually finishes —
        freeing it early would admit fresh load on top of zombies."""
        from hops_tpu.modelrepo import serving

        script = tmp_path / "p.py"
        script.write_text(
            "import time\n"
            "class Predict:\n"
            "    def predict(self, instances):\n"
            "        if instances and instances[0] == ['slow']:\n"
            "            time.sleep(0.6)\n"
            "        return instances\n"
        )
        serving.create_or_update(
            "chaos-zombie", model_path=str(tmp_path), model_server="PYTHON",
            resilience_config={"max_inflight": 1, "deadline_s": 0.15,
                               "breaker_failures": 100})
        serving.start("chaos-zombie")
        port = serving._load_registry()["chaos-zombie"]["port"]
        try:
            code, _, _ = _post(port, "chaos-zombie", {"instances": [["slow"]]})
            assert code == 504  # deadline hit; predict zombies on
            code, _, headers = _post(port, "chaos-zombie",
                                     {"instances": [[1]]})
            assert code == 503 and "Retry-After" in headers  # slot held
            time.sleep(0.6)  # zombie finishes, slot frees
            assert _post(port, "chaos-zombie",
                         {"instances": [[2]]})[0] == 200
        finally:
            serving.stop("chaos-zombie")

    def test_handler_faults_open_breaker_and_flip_healthz(self, tmp_path):
        from hops_tpu.modelrepo import serving
        from hops_tpu.runtime import flight

        flight_base = flight.FLIGHT.seq
        port = self._start(
            tmp_path, "chaos-brk",
            {"breaker_failures": 2, "breaker_reset_s": 0.3})
        try:
            assert _healthz(port) == (200, {"status": "ok",
                                            "breaker": "closed"})
            faultinject.arm("serving.handle=error:RuntimeError@times=2")
            for _ in range(2):
                code, _, _ = _post(port, "chaos-brk", {"instances": [[1]]})
                assert code == 500
            # Breaker open: fast 503 + Retry-After, /healthz unready.
            code, _, headers = _post(port, "chaos-brk", {"instances": [[1]]})
            assert code == 503 and "Retry-After" in headers
            assert _counter("hops_tpu_serving_shed_total",
                            model="chaos-brk", reason="breaker") >= 1
            code, body = _healthz(port)
            assert code == 503 and body["breaker"] == "open"
            # Injection exhausted; the half-open probe heals it.
            time.sleep(0.35)
            code, body, _ = _post(port, "chaos-brk", {"instances": [[7]]})
            assert code == 200 and body["predictions"] == [[7]]
            assert _healthz(port)[0] == 200
            # The flight recorder kept the causal black-box story: the
            # injected faults fired, THEN the breaker opened, and the
            # half-open heal closed it again — in sequence order.
            events = flight.FLIGHT.events(after_seq=flight_base)
            fired = [e for e in events if e["kind"] == "fault_fired"
                     and e["data"]["point"] == "serving.handle"]
            assert len(fired) == 2
            opened = next(e for e in events
                          if e["kind"] == "breaker_transition"
                          and e["data"]["to"] == "open")
            closed = next(e for e in events
                          if e["kind"] == "breaker_transition"
                          and e["data"]["to"] == "closed"
                          and e["seq"] > opened["seq"])
            assert max(e["seq"] for e in fired) < opened["seq"] \
                < closed["seq"]
        finally:
            serving.stop("chaos-brk")

    def test_engine_queue_full_sheds_overload_without_breaker_strike(
            self, tmp_path):
        """A bounded submit queue refusing work (``qos.QueueFullError``
        — the LM engine's ``max_queue`` admission bound) is a SHED, not
        a failure: 503 + ``Retry-After``, ``reason="overload"``, and no
        breaker strike — the model is healthy, just full."""
        from hops_tpu.modelrepo import serving

        script = tmp_path / "p.py"
        script.write_text(
            "from hops_tpu.runtime import qos\n"
            "class Predict:\n"
            "    def predict(self, instances):\n"
            "        if instances and instances[0] == ['burst']:\n"
            "            raise qos.QueueFullError('submit queue full "
            "(2/2 queued); retry later')\n"
            "        return instances\n"
        )
        serving.create_or_update(
            "chaos-qfull", model_path=str(tmp_path), model_server="PYTHON",
            resilience_config={"breaker_failures": 2})
        serving.start("chaos-qfull")
        port = serving._load_registry()["chaos-qfull"]["port"]
        try:
            before = _counter("hops_tpu_serving_shed_total",
                              model="chaos-qfull", reason="overload")
            for _ in range(3):  # would open the breaker if these struck
                code, body, headers = _post(port, "chaos-qfull",
                                            {"instances": [["burst"]]})
                assert code == 503 and headers["Retry-After"] == "1"
                assert "QueueFullError" in body["error"]
            assert _counter("hops_tpu_serving_shed_total",
                            model="chaos-qfull", reason="overload") \
                == before + 3
            # No breaker strike: the very next request serves.
            code, body, _ = _post(port, "chaos-qfull", {"instances": [[5]]})
            assert code == 200 and body["predictions"] == [[5]]
            assert _healthz(port)[0] == 200
        finally:
            serving.stop("chaos-qfull")


# -- search-trial and pubsub chaos --------------------------------------------


class TestSearchTrialChaos:
    def test_flaky_trial_retried_before_failure(self):
        from hops_tpu.search.drivers import grid_search

        def train(lr):
            return {"metric": lr * 2}

        faultinject.arm("search.trial=error:OSError@times=1")
        _, summary = grid_search(
            train, {"lr": [1.0, 2.0]}, max_parallel=1,
            retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.01,
                                     seed=0))
        # The injected failure was retried, not recorded as a failure.
        assert summary["num_trials"] == 2
        assert all(t["metric"] is not None
                   for t in summary["trials"].values())
        assert summary["best_metric"] == 4.0

    def test_exhausted_retries_still_mark_failed_not_crash(self):
        from hops_tpu.search.drivers import grid_search

        faultinject.arm("search.trial=error:OSError")  # every attempt
        _, summary = grid_search(
            lambda lr: {"metric": lr}, {"lr": [1.0]}, max_parallel=1,
            retry_policy=RetryPolicy(max_attempts=2, base_delay_s=0.01,
                                     seed=0))
        assert summary["num_trials"] == 1
        assert summary["best_metric"] is None  # failed, search survived


class TestPubsubChaos:
    def test_consumer_survives_corrupt_record(self):
        from hops_tpu.messaging import pubsub

        pubsub.create_topic("chaos-topic")
        consumer = pubsub.Consumer("chaos-topic", from_beginning=True)
        producer = pubsub.Producer("chaos-topic")
        faultinject.arm("pubsub.publish=corrupt@times=1")
        producer.send({"seq": 0})  # corrupted on the wire
        producer.send({"seq": 1})
        producer.send({"seq": 2})
        faultinject.disarm()
        records = consumer.poll()
        # The mangled record is skipped, not a wedge: its newline
        # framing survives corruption, so ONLY it is lost — the healthy
        # records around it come through and the offset keeps moving.
        assert [r["value"]["seq"] for r in records] == [1, 2]
        producer.send({"seq": 3})
        assert [r["value"]["seq"] for r in consumer.poll()] == [3]


@pytest.mark.slow  # compiles the tiny LM engine programs (jit) — slow tier
class TestLMEngineDispatchFaults:
    """The ``lm_engine.dispatch`` fault point: an injected transient
    dispatch error must fail ONLY the affected requests — their slots
    and (paged) blocks freed, the error surfaced per ticket / as a 5xx
    — and must never wedge the scheduler loop."""

    def _engine(self, paged: bool):
        import jax
        import jax.numpy as jnp

        from hops_tpu.models.transformer import TransformerLM
        from hops_tpu.modelrepo.lm_engine import LMEngine

        tiny = dict(
            vocab_size=64, d_model=32, num_heads=4, num_layers=2,
            dtype=jnp.float32, attention_impl="reference",
            max_decode_len=64,
        )
        model = TransformerLM(**tiny, ragged_decode=True)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        kw = (
            dict(kv_page_size=8, prefill_chunk=8)
            if paged else dict(prefill_buckets=(8, 16))
        )
        return LMEngine(model, params, slots=2, **kw)

    @pytest.mark.parametrize("paged", [False, True])
    def test_transient_dispatch_error_fails_only_inflight(self, paged):
        engine = self._engine(paged)
        rs = np.random.RandomState(0)
        t1 = engine.submit(rs.randint(1, 64, (10,)), max_new_tokens=6)
        t2 = engine.submit(rs.randint(1, 64, (10,)), max_new_tokens=6)
        engine.step()
        engine.step()  # both requests decoding
        faultinject.arm("lm_engine.dispatch=error:RuntimeError@times=1")
        assert engine.step() == []  # the failed wave finishes nobody
        faultinject.disarm()
        # Both in-flight requests failed, slots and blocks freed...
        for t in (t1, t2):
            err = engine.error(t)
            assert isinstance(err, RuntimeError), (t, err)
            assert engine.result(t) is None
        assert all(st is None for st in engine._slot_state)
        if paged:
            assert engine._pool.used == 0
        # ...and the scheduler keeps serving: a fresh request completes.
        t3 = engine.submit(rs.randint(1, 64, (8,)), max_new_tokens=4)
        res = engine.run()
        assert len(res[t3]) == 4
        assert engine.take_error(t1) is not None
        assert engine.take_error(t1) is None  # consumed
        assert _counter("hops_tpu_lm_dispatch_failures_total") >= 1

    def test_queued_requests_survive_the_failed_wave(self):
        engine = self._engine(True)
        rs = np.random.RandomState(1)
        inflight = engine.submit(rs.randint(1, 64, (10,)), max_new_tokens=6)
        engine.step()
        engine.step()
        # Fill every slot's worth and more — the tail stays queued.
        queued = [
            engine.submit(rs.randint(1, 64, (10,)), max_new_tokens=4)
            for _ in range(3)
        ]
        faultinject.arm("lm_engine.dispatch=error:RuntimeError@times=1")
        engine.step()
        faultinject.disarm()
        assert isinstance(engine.error(inflight), RuntimeError)
        res = engine.run()
        for t in queued:  # queued work was never "in flight": it runs
            assert len(res[t]) == 4, t
        assert engine._pool.used == 0

    def test_serving_surfaces_dispatch_failure_as_500(self):
        """End to end through the HTTP surface: the affected caller gets
        a 5xx, the endpoint stays up, and the next request succeeds."""
        import jax
        import jax.numpy as jnp

        from hops_tpu.models.transformer import TransformerLM
        from hops_tpu.modelrepo import registry, serving

        tiny = dict(
            vocab_size=64, d_model=32, num_heads=4, num_layers=2,
            dtype=jnp.float32, attention_impl="reference",
            max_decode_len=64,
        )
        plain = TransformerLM(**tiny)
        params = plain.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        registry.save_flax(plain, params, "chaos-lm", metrics={"loss": 1.0})
        serving.create_or_update(
            "chaos-lm", model_name="chaos-lm", model_server="LM",
            lm_config={"slots": 2, "kv_page_size": 8, "prefill_chunk": 8},
        )
        serving.start("chaos-lm")
        try:
            port = serving._load_registry()["chaos-lm"]["port"]
            # Warm request (compiles outside the armed window).
            code, body, _ = _post(
                port, "chaos-lm",
                {"instances": [{"prompt": [1, 2, 3], "max_new_tokens": 2}]},
                timeout=120,
            )
            assert code == 200, body
            # The fault must hit a wave with the request IN FLIGHT (a
            # queued-only request rightly survives — step-level faults
            # fail only admitted work), so skip the first two engine
            # iterations deterministically: passage 1 admits + first
            # chunk, passage 2 decodes, passage 3 fires mid-stream.
            faultinject.arm(
                "lm_engine.dispatch=error:RuntimeError@times=1,after=2"
            )
            code, body, _ = _post(
                port, "chaos-lm",
                {"instances": [{"prompt": [4, 5, 6],
                                "max_new_tokens": 16}]},
                timeout=120,
            )
            faultinject.disarm()
            assert code == 500, body
            assert "dispatch failed" in body["error"]
            # The scheduler survived: the endpoint serves again.
            code, body, _ = _post(
                port, "chaos-lm",
                {"instances": [{"prompt": [4, 5, 6], "max_new_tokens": 4}]},
                timeout=120,
            )
            assert code == 200, body
            assert len(body["predictions"][0]) == 4
        finally:
            serving.stop("chaos-lm")
