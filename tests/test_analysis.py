"""graftlint unit coverage: per-rule true-positive AND must-not-flag
snippets, suppression pragmas, baseline round-trip, CLI exit codes, and
the JSON output schema.

Each rule's contract is pinned by a pair: a snippet that MUST produce
the finding and a near-miss that must NOT (the false-positive budget is
what makes a zero-findings gate enforceable — one spurious finding and
the tree rots into blanket suppressions)."""

from __future__ import annotations

import json
import textwrap
from pathlib import Path

import pytest

from hops_tpu import analysis
from hops_tpu.analysis import baseline as baseline_mod
from hops_tpu.analysis import cli, engine


def lint_code(tmp_path: Path, code: str, rule: str | None = None,
              docs: str | None = None, filename: str = "snip.py"):
    """Write ``code`` into a scratch tree and lint it."""
    target = tmp_path / filename
    target.write_text(textwrap.dedent(code))
    docs_path = None
    if docs is not None:
        docs_path = tmp_path / "operations.md"
        docs_path.write_text(docs)
    rules = None
    if rule is not None:
        rules = [r for r in engine.all_rules() if r.name == rule]
        assert rules, f"unknown rule {rule}"
    return engine.run([target], root=tmp_path, docs_path=docs_path, rules=rules)


def rule_names(findings) -> list[str]:
    return [f.rule for f in findings]


# -- jit-purity ---------------------------------------------------------------


def test_jit_purity_flags_print_time_random_in_decorated_fn(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import random
        import time
        import jax

        @jax.jit
        def step(x):
            print("x =", x)
            t = time.monotonic()
            r = random.random()
            return x + t + r
        """,
        rule="jit-purity",
    )
    messages = " | ".join(f.message for f in findings)
    assert len(findings) == 3
    assert "`print`" in messages
    assert "time.monotonic" in messages
    assert "random.random" in messages
    assert all(f.symbol == "step" for f in findings)


def test_jit_purity_flags_telemetry_and_global_in_step_factory(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        _steps = 0

        def make_train_step(counter):
            def train_step(state, batch):
                global _steps
                counter = self._m_steps
                counter.inc()
                return state
            return train_step
        """,
        rule="jit-purity",
    )
    messages = " | ".join(f.message for f in findings)
    assert "`global _steps`" in messages
    assert "telemetry mutation" in messages


def test_jit_purity_must_not_flag_untraced_or_sanctioned(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import time
        import jax
        from jax import random  # jax.random, not stdlib

        def host_side():
            print("fine: not traced")
            return time.time()

        @jax.jit
        def step(x, key):
            jax.debug.print("x = {}", x)       # sanctioned escape hatch
            return x + random.normal(key, ())  # jax.random, fine
        """,
        rule="jit-purity",
    )
    assert findings == []


def test_jit_purity_sees_fn_passed_to_jit_call(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import jax

        def impure(x):
            print(x)
            return x

        compiled = jax.jit(impure)
        """,
        rule="jit-purity",
    )
    assert rule_names(findings) == ["jit-purity"]


# -- use-after-donation -------------------------------------------------------


def test_donation_flags_read_after_donating_call(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import jax

        def train(f, state, batch):
            g = jax.jit(f, donate_argnums=(0,))
            out = g(state, batch)
            return state.params  # state's buffer belongs to XLA now
        """,
        rule="use-after-donation",
    )
    assert len(findings) == 1
    assert "`state` read after being donated" in findings[0].message


def test_donation_flags_unrebound_loop_argument(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        def train(strategy, fn, state, batches):
            step = strategy.step(fn)
            for b in batches:
                out = step(state, b)
            return out
        """,
        rule="use-after-donation",
    )
    assert len(findings) == 1
    assert "never rebound" in findings[0].message


def test_donation_must_not_flag_rebinding_patterns(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import jax

        def train(strategy, fn, state, batches):
            step = strategy.step(fn)
            for b in batches:
                state, metrics = step(state, b)  # rebound: stream-carried
            return state, metrics

        def one_shot(f, x, y):
            g = jax.jit(f, donate_argnums=(0,))
            x = g(x, y)  # rebound in the same statement
            return x

        def no_donation(strategy, fn, state, batches):
            step = strategy.step(fn, donate_state=False)
            for b in batches:
                out = step(state, b)  # nothing donated
            return out
        """,
        rule="use-after-donation",
    )
    assert findings == []


# -- host-sync-in-loop --------------------------------------------------------


def test_host_sync_flags_item_float_asarray_blocking_in_step_loop(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import jax
        import numpy as np

        def train(step, state, batches):
            for batch in batches:
                state, metrics = step(state, batch)
                loss = float(metrics["loss"])
                acc = metrics["accuracy"].item()
                host = np.asarray(metrics["grads"])
                jax.block_until_ready(state)
            return state
        """,
        rule="host-sync-in-loop",
    )
    assert len(findings) == 4
    messages = " | ".join(f.message for f in findings)
    assert "float(metrics['loss'])" in messages
    assert ".item()" in messages
    assert "np.asarray" in messages
    assert "block_until_ready" in messages


def test_host_sync_must_not_flag_outside_loop_or_non_step_loop(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import numpy as np

        def train(step, state, batches):
            for batch in batches:
                state, metrics = step(state, batch)
            return float(metrics["loss"])  # ONE sync after the loop: fine

        def host_math(rows):
            total = 0.0
            for r in rows:               # not a step loop
                total += float(np.mean(r))
            return total
        """,
        rule="host-sync-in-loop",
    )
    assert findings == []


# -- lock-discipline ----------------------------------------------------------


def test_lock_discipline_flags_unguarded_attribute_access(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._free = []  # guarded by: self._lock

            def bad(self):
                return self._free.pop()
        """,
        rule="lock-discipline",
    )
    assert len(findings) == 1
    assert findings[0].symbol == "Pool.bad"
    assert "guarded by `self._lock`" in findings[0].message


def test_lock_discipline_module_level_guard(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import threading

        _servers = {}  # guarded by: _lock
        _lock = threading.Lock()

        def good(name):
            with _lock:
                return name in _servers

        def bad(name):
            return _servers.get(name)
        """,
        rule="lock-discipline",
    )
    assert len(findings) == 1
    assert findings[0].symbol == "bad"


def test_lock_discipline_must_not_flag_sanctioned_shapes(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import threading

        class Base:
            def __init__(self):
                self._lock = threading.Lock()
                self._children = {}  # guarded by: self._lock
                self._children["warm"] = 1  # __init__ is single-threaded

            def labels(self):
                with self._lock:
                    return self._child()

            def _child(self):  # guarded by: self._lock
                return self._children.get("x")

        class Sub(Base):
            def samples(self):
                with self._lock:
                    return list(self._children.items())
        """,
        rule="lock-discipline",
    )
    assert findings == []


def test_lock_discipline_covers_subclasses(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import threading

        class Base:
            def __init__(self):
                self._lock = threading.Lock()
                self._children = {}  # guarded by: self._lock

        class Sub(Base):
            def bad(self):
                return len(self._children)
        """,
        rule="lock-discipline",
    )
    assert [f.symbol for f in findings] == ["Sub.bad"]


# -- metric-name-consistency --------------------------------------------------

_METRIC_SNIPPET = """
from hops_tpu.telemetry.metrics import REGISTRY

c = REGISTRY.counter("hops_tpu_widget_total", "Widgets")
"""


def test_metric_consistency_flags_undocumented_metric(tmp_path):
    findings = lint_code(
        tmp_path, _METRIC_SNIPPET,
        rule="metric-name-consistency",
        docs="# Ops\n\nNo metrics table here.\n",
    )
    assert len(findings) == 1
    assert "missing from docs/operations.md" in findings[0].message


def test_metric_consistency_documented_metric_is_clean(tmp_path):
    findings = lint_code(
        tmp_path, _METRIC_SNIPPET,
        rule="metric-name-consistency",
        docs="# Ops\n\n- `hops_tpu_widget_total` counts widgets.\n",
    )
    assert findings == []


def test_metric_consistency_flags_type_and_bucket_conflicts(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        from hops_tpu.telemetry.metrics import REGISTRY

        a = REGISTRY.counter("hops_tpu_thing_total", "As counter")
        b = REGISTRY.gauge("hops_tpu_thing_total", "As gauge")
        h1 = REGISTRY.histogram("hops_tpu_lat_seconds", "L", buckets=(0.1, 1.0))
        h2 = REGISTRY.histogram("hops_tpu_lat_seconds", "L", buckets=(0.5, 5.0))
        h3 = REGISTRY.histogram("hops_tpu_lat_seconds", "L")  # read-back: fine
        """,
        rule="metric-name-consistency",
        docs="`hops_tpu_thing_total` `hops_tpu_lat_seconds`",
    )
    assert len(findings) == 2
    messages = " | ".join(f.message for f in findings)
    assert "one name, one type" in messages
    assert "quantiles would disagree" in messages


def test_metric_consistency_resolves_module_constants(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        from hops_tpu.telemetry.metrics import REGISTRY

        HEARTBEAT = "hops_tpu_beat_time"
        g = REGISTRY.gauge(HEARTBEAT, "Last beat")
        """,
        rule="metric-name-consistency",
        docs="nothing documented",
    )
    assert len(findings) == 1
    assert "hops_tpu_beat_time" in findings[0].message


# -- debug-surface-docs -------------------------------------------------------

_DEBUG_SNIPPET = """
from hops_tpu.runtime import flight


def handler(path):
    if path == "/debug/widgets":
        flight.record("widget_jam", count=3)
        return True
    return False
"""


def test_debug_surfaces_flags_undocumented_route_and_kind(tmp_path):
    findings = lint_code(
        tmp_path, _DEBUG_SNIPPET,
        rule="debug-surface-docs",
        docs="# Ops\n\nNo debug surfaces documented here.\n",
    )
    assert len(findings) == 2
    messages = " | ".join(f.message for f in findings)
    assert "/debug/widgets" in messages
    assert "widget_jam" in messages


def test_debug_surfaces_documented_surfaces_are_clean(tmp_path):
    findings = lint_code(
        tmp_path, _DEBUG_SNIPPET,
        rule="debug-surface-docs",
        docs="# Ops\n\n`GET /debug/widgets` serves the jam report; the "
             "flight recorder's `widget_jam` kind records each jam.\n",
    )
    assert findings == []


def test_debug_surfaces_kind_docs_match_is_whole_word(tmp_path):
    # `widget_jam` embedded in a longer identifier must not count as
    # documentation (the sibling metric rule holds the same line).
    findings = lint_code(
        tmp_path, _DEBUG_SNIPPET,
        rule="debug-surface-docs",
        docs="# Ops\n\n`GET /debug/widgets` and `widget_jammed_total`.\n",
    )
    assert len(findings) == 1
    assert "widget_jam" in findings[0].message


def test_debug_surfaces_covers_admin_routes(tmp_path):
    """Admin routes are operator verbs (drain, capture start/stop) —
    an undocumented one is a control plane nobody can operate; the
    rule holds /admin/* literals to the same docs contract as
    /debug/*."""
    snippet = """
def handler(path):
    if path == "/admin/capture/start":
        return True
    return path == "/admin/widgets/drain"
"""
    findings = lint_code(
        tmp_path, snippet,
        rule="debug-surface-docs",
        docs="# Ops\n\nNo admin routes documented here.\n",
    )
    assert len(findings) == 2
    messages = " | ".join(f.message for f in findings)
    assert "/admin/capture/start" in messages
    assert "/admin/widgets/drain" in messages
    assert lint_code(
        tmp_path, snippet,
        rule="debug-surface-docs",
        docs="# Ops\n\n`POST /admin/capture/start` arms capture; "
             "`POST /admin/widgets/drain` drains the widgets.\n",
    ) == []


def test_debug_surfaces_ignores_inflight_lookalike_receivers(tmp_path):
    # `inflight` trackers are everywhere in the serving stack; a
    # suffix match on the receiver would demand their record() calls
    # be documented as flight-recorder kinds.
    findings = lint_code(
        tmp_path,
        """
        class _Tracker:
            def record(self, kind, **kw):
                pass

        inflight = _Tracker()
        self_inflight = _Tracker()
        inflight.record("probe_started", port=1)
        self_inflight.record("slot_taken", n=2)
        """,
        rule="debug-surface-docs",
        docs="# Ops\n\nnothing documented\n",
    )
    assert findings == []


def test_debug_surfaces_skips_dynamic_kinds_and_bare_prefix(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        from hops_tpu.runtime import flight

        PREFIX = "/debug/"  # a bare prefix, not a route
        kind = "wid" + "get_jam"  # dynamically built: out of static reach
        flight.record(kind, count=1)
        """,
        rule="debug-surface-docs",
        docs="# Ops\n\nnothing documented\n",
    )
    assert findings == []


def test_debug_surfaces_each_surface_reported_once(tmp_path):
    # The same undocumented route/kind referenced from several sites
    # (server, client, tests) is one missing doc entry, not N findings.
    findings = lint_code(
        tmp_path,
        """
        from hops_tpu.runtime import flight

        A = "/debug/widgets"
        B = "/debug/widgets"
        flight.record("widget_jam", where="a")
        flight.record("widget_jam", where="b")
        """,
        rule="debug-surface-docs",
        docs="# Ops\n\nnothing documented\n",
    )
    assert len(findings) == 2


def test_debug_surfaces_tree_is_clean():
    """Every /debug/* route and flight-recorder event kind the package
    ships is documented in docs/operations.md — zero findings, no
    baseline entries (the docs' catalogs ARE the operator contract)."""
    from hops_tpu.analysis.cli import default_docs, default_target, lint_root

    pkg = default_target()
    root = lint_root([pkg])
    rules = [r for r in engine.all_rules() if r.name == "debug-surface-docs"]
    findings = engine.run(
        [pkg], root=root, docs_path=default_docs(root), rules=rules
    )
    assert findings == [], "\n".join(f.render() for f in findings)


# -- swallowed-exception ------------------------------------------------------


def test_swallowed_exception_flags_bare_and_broad_pass(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        def a():
            try:
                return 1
            except:
                return None

        def b():
            try:
                return 1
            except Exception:
                pass
        """,
        rule="swallowed-exception",
    )
    assert len(findings) == 2
    assert "bare `except:`" in findings[0].message
    assert "swallows the error" in findings[1].message


def test_swallowed_exception_must_not_flag_handled_or_narrow(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import logging

        def a():
            try:
                return 1
            except Exception:
                logging.exception("boom")  # handled: logged
                return None

        def b(path):
            try:
                path.unlink()
            except FileNotFoundError:
                pass  # narrow type: a legitimate "already gone"
        """,
        rule="swallowed-exception",
    )
    assert findings == []


# -- naked-retry-loop ---------------------------------------------------------


def test_naked_retry_loop_flags_constant_sleep_retry(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import time

        def fetch(url):
            for attempt in range(5):
                try:
                    return read(url)
                except OSError:
                    time.sleep(1.0)

        def drain(q):
            while True:
                try:
                    q.pop()
                except IndexError:
                    time.sleep(0.5)
        """,
        rule="naked-retry-loop",
    )
    assert len(findings) == 2
    assert all(f.rule == "naked-retry-loop" for f in findings)
    assert "lockstep" in findings[0].message


def test_naked_retry_loop_reports_innermost_loop_once(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import time

        def nested():
            while True:
                for attempt in range(3):
                    try:
                        return go()
                    except OSError:
                        time.sleep(2)
        """,
        rule="naked-retry-loop",
    )
    assert len(findings) == 1  # the inner loop only


def test_naked_retry_loop_must_not_flag_polls_or_computed_backoff(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import time

        def poll_until_done(job):
            # No exception handling: a watch loop, not a retry loop.
            while not job.done():
                time.sleep(0.1)

        def retry_with_backoff(fn, policy):
            for attempt in range(5):
                try:
                    return fn()
                except OSError:
                    time.sleep(policy.delay(attempt))  # computed: fine

        def one_shot_retry(fn):
            # try/except + sleep but NOT in a loop.
            try:
                return fn()
            except OSError:
                time.sleep(1)
                return fn()

        def spawner(pool, jobs):
            # The loop only DEFINES a helper that retries; the sleep
            # runs per helper call, not per loop iteration.
            while jobs:
                def worker(job=jobs.pop()):
                    try:
                        return job()
                    except OSError:
                        time.sleep(1)
                pool.submit(worker)
        """,
        rule="naked-retry-loop",
    )
    assert findings == []


def test_naked_retry_loop_sanctions_resilience_module(tmp_path):
    code = """
    import time

    def call(fn):
        for attempt in range(3):
            try:
                return fn()
            except OSError:
                time.sleep(0.5)
    """
    (tmp_path / "hops_tpu" / "runtime").mkdir(parents=True)
    flagged = lint_code(tmp_path, code, rule="naked-retry-loop",
                        filename="other.py")
    assert len(flagged) == 1
    sanctioned = lint_code(
        tmp_path, code, rule="naked-retry-loop",
        filename="hops_tpu/runtime/resilience.py")
    assert sanctioned == []


# -- blocking-call-no-deadline ------------------------------------------------


FLEET_FILE = "hops_tpu/modelrepo/fleet/snip.py"


def test_blocking_call_flags_deadlineless_urlopen_in_fleet_code(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        import socket
        import urllib.request

        def probe(port):
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz") as r:
                return r.status

        def connect(port):
            return socket.create_connection(("127.0.0.1", port))
        """,
        rule="blocking-call-no-deadline",
        filename=FLEET_FILE,
    )
    assert len(findings) == 2
    assert all(f.rule == "blocking-call-no-deadline" for f in findings)
    assert "urllib.request.urlopen" in findings[0].message
    assert "timeout=" in findings[0].message


def test_blocking_call_not_flagged_with_timeout_or_deadline_wrapper(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        import socket
        import urllib.request

        from hops_tpu.runtime.resilience import with_deadline

        def probe(port, budget):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=budget
            ) as r:
                return r.status

        def connect(port):
            return socket.create_connection(("127.0.0.1", port), 0.5)

        def probe_positional(url, body):
            return urllib.request.urlopen(url, body, 2.0)  # 3rd positional = timeout

        def forward(url, body):
            return with_deadline(
                lambda: urllib.request.urlopen(url, data=body), 2.0)

        def not_a_network_get(d):
            return d.get("key")  # dict idiom, not requests.get
        """,
        rule="blocking-call-no-deadline",
        filename=FLEET_FILE,
    )
    assert findings == []


def test_blocking_call_scoped_to_fleet_files_only(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    code = """
    import urllib.request

    def fetch(url):
        return urllib.request.urlopen(url)
    """
    # The identical call outside fleet/ is some other module's business
    # (serving clients pass explicit timeouts by convention, not rule).
    outside = lint_code(tmp_path, code, rule="blocking-call-no-deadline",
                        filename="hops_tpu/modelrepo/client.py")
    assert outside == []
    inside = lint_code(tmp_path, code, rule="blocking-call-no-deadline",
                       filename=FLEET_FILE)
    assert len(inside) == 1


def test_blocking_call_tree_is_clean():
    """The fleet control plane itself must hold the budget discipline
    the rule enforces — zero findings, no baseline entries."""
    import hops_tpu

    fleet_dir = Path(hops_tpu.__file__).parent / "modelrepo" / "fleet"
    rules = [r for r in engine.all_rules()
             if r.name == "blocking-call-no-deadline"]
    findings = engine.run([fleet_dir], root=fleet_dir.parent.parent.parent,
                          rules=rules)
    assert findings == []


# -- relay-json-roundtrip -----------------------------------------------------


def test_relay_roundtrip_flags_parse_then_redump(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        import json

        def forward(resp):
            payload = json.loads(resp.read())
            return json.dumps(payload).encode()

        def reply(body):
            return json.dumps(json.loads(body))
        """,
        rule="relay-json-roundtrip",
        filename=FLEET_FILE,
    )
    assert len(findings) == 2
    assert all(f.rule == "relay-json-roundtrip" for f in findings)
    assert "re-json.dumps'ed" in findings[0].message or \
        "never read" in findings[0].message


def test_relay_roundtrip_not_flagged_when_object_is_read(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        import json

        def merge(body, extra):
            payload = json.loads(body)        # inspected below: fine
            payload["debug"] = extra
            return json.dumps(payload)

        def inspect(body):
            obj = json.loads(body)            # read, never re-dumped
            return obj.get("instances")

        def branch(body):
            p = json.loads(body)
            if p.get("error"):                # conditional read
                return json.dumps(p)
            return b"{}"

        def dumps_something_else(body, other):
            _ = json.loads(body)  # noqa — unused parse, not a re-dump
            return json.dumps(other)
        """,
        rule="relay-json-roundtrip",
        filename=FLEET_FILE,
    )
    assert findings == []


def test_relay_roundtrip_scoped_to_fleet_and_serving(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    (tmp_path / "hops_tpu" / "featurestore").mkdir(parents=True)
    code = """
    import json

    def echo(body):
        return json.dumps(json.loads(body))
    """
    outside = lint_code(tmp_path, code, rule="relay-json-roundtrip",
                        filename="hops_tpu/featurestore/feed.py")
    assert outside == []
    for scoped in (FLEET_FILE, "hops_tpu/modelrepo/serving.py"):
        inside = lint_code(tmp_path, code, rule="relay-json-roundtrip",
                           filename=scoped)
        assert len(inside) == 1, scoped


def test_relay_roundtrip_tree_is_clean():
    """The relay tier itself holds the zero-copy discipline — zero
    findings over fleet/ + serving.py, no baseline entries."""
    import hops_tpu

    modelrepo = Path(hops_tpu.__file__).parent / "modelrepo"
    rules = [r for r in engine.all_rules()
             if r.name == "relay-json-roundtrip"]
    findings = engine.run(
        [modelrepo / "fleet", modelrepo / "serving.py"],
        root=modelrepo.parent.parent, rules=rules,
    )
    assert findings == []


# -- json-on-hot-wire ---------------------------------------------------------


ROUTER_FILE = "hops_tpu/modelrepo/fleet/router.py"


def test_json_on_hot_wire_flags_body_codec_calls(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        import json

        def handle(body):
            payload = json.loads(body)
            return json.dumps(payload).encode()

        def handle_default(raw_body):
            return json.loads(raw_body or b"{}")
        """,
        rule="json-on-hot-wire",
        filename=ROUTER_FILE,
    )
    assert len(findings) == 3
    assert all(f.rule == "json-on-hot-wire" for f in findings)
    assert any("json.loads" in f.message for f in findings)
    assert any("json.dumps" in f.message for f in findings)


def test_json_on_hot_wire_must_not_flag(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        import json

        def config(path):
            spec = json.loads(path.read_text())   # not a body variable
            return spec

        def dumps_no_encode(body):
            return json.dumps({"n": 1})           # str, never hits wire

        def other_codec(body):
            import pickle
            return pickle.loads(body)             # not json

        def loads_of_literal():
            return json.loads('{"a": 1}')         # constant, not a body
        """,
        rule="json-on-hot-wire",
        filename=ROUTER_FILE,
    )
    assert findings == []


def test_json_on_hot_wire_scoped_to_wire_tier(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    (tmp_path / "hops_tpu" / "featurestore").mkdir(parents=True)
    code = """
    import json

    def handle(body):
        return json.loads(body)
    """
    outside = lint_code(tmp_path, code, rule="json-on-hot-wire",
                        filename="hops_tpu/featurestore/offline.py")
    assert outside == []
    for scoped in (ROUTER_FILE, "hops_tpu/modelrepo/serving.py",
                   "hops_tpu/featurestore/online_serving.py"):
        inside = lint_code(tmp_path, code, rule="json-on-hot-wire",
                           filename=scoped)
        assert len(inside) == 1, scoped


def test_json_on_hot_wire_tree_is_clean():
    """Every JSON codec call left on the wire tier is a *negotiated*
    fallback or control-plane site carrying a justified disable pragma
    — zero un-annotated findings, no baseline entries."""
    import hops_tpu

    pkg = Path(hops_tpu.__file__).parent
    rules = [r for r in engine.all_rules() if r.name == "json-on-hot-wire"]
    findings = engine.run(
        [pkg / "modelrepo", pkg / "featurestore"],
        root=pkg.parent, rules=rules,
    )
    assert findings == [], "\n".join(f.render() for f in findings)


# -- suppression --------------------------------------------------------------


def test_inline_disable_silences_one_line(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        def a():
            try:
                return 1
            except Exception:  # graftlint: disable=swallowed-exception
                pass

        def b():
            try:
                return 2
            except Exception:
                pass
        """,
        rule="swallowed-exception",
    )
    assert [f.symbol for f in findings] == ["b"]


def test_file_disable_silences_whole_file(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        # graftlint: disable-file=swallowed-exception

        def a():
            try:
                return 1
            except:
                pass
        """,
        rule="swallowed-exception",
    )
    assert findings == []


# -- fingerprints and baseline ------------------------------------------------

_FINDING_SNIPPET = """
def a():
    try:
        return 1
    except Exception:
        pass
"""


def test_fingerprint_survives_line_shift(tmp_path):
    (tmp_path / "one").mkdir()
    (tmp_path / "two").mkdir()
    f1 = lint_code(tmp_path / "one", _FINDING_SNIPPET)
    f2 = lint_code(tmp_path / "two", "\n\n\n# moved down\n" + _FINDING_SNIPPET)
    assert len(f1) == len(f2) == 1
    assert f1[0].line != f2[0].line
    assert f1[0].fingerprint == f2[0].fingerprint


def test_baseline_round_trip(tmp_path):
    findings = lint_code(tmp_path, _FINDING_SNIPPET)
    assert len(findings) == 1
    bl_path = tmp_path / "baseline.json"
    baseline_mod.write(bl_path, findings)

    # The generated placeholder must NOT load — justification is human work.
    with pytest.raises(baseline_mod.BaselineError, match="placeholder"):
        baseline_mod.Baseline.load(bl_path)

    data = json.loads(bl_path.read_text())
    data["entries"][0]["justification"] = "teardown path; close() is explicit everywhere else"
    bl_path.write_text(json.dumps(data))
    bl = baseline_mod.Baseline.load(bl_path)

    new, baselined, stale = bl.split(findings)
    assert new == [] and len(baselined) == 1 and stale == []

    # Finding gone -> the entry goes stale (and is reported, not hidden).
    new, baselined, stale = bl.split([])
    assert new == [] and baselined == [] and len(stale) == 1


def test_baseline_rejects_missing_justification(tmp_path):
    bl_path = tmp_path / "baseline.json"
    bl_path.write_text(json.dumps({
        "version": 1,
        "entries": [{"rule": "r", "path": "p.py", "message": "m", "justification": "  "}],
    }))
    with pytest.raises(baseline_mod.BaselineError, match="justification"):
        baseline_mod.Baseline.load(bl_path)


# -- CLI ----------------------------------------------------------------------


def test_cli_exit_0_on_clean_tree(tmp_path, capsys):
    (tmp_path / "clean.py").write_text("x = 1\n")
    assert cli.main([str(tmp_path)]) == cli.EXIT_CLEAN


def test_cli_exit_1_on_findings(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(_FINDING_SNIPPET)
    assert cli.main([str(tmp_path)]) == cli.EXIT_FINDINGS
    out = capsys.readouterr().out
    assert "swallowed-exception" in out


def test_cli_exit_2_on_usage_errors(tmp_path, capsys):
    assert cli.main([str(tmp_path / "missing")]) == cli.EXIT_USAGE
    (tmp_path / "ok.py").write_text("x = 1\n")
    assert cli.main([str(tmp_path), "--rules", "no-such-rule"]) == cli.EXIT_USAGE
    bad_bl = tmp_path / "bl.json"
    bad_bl.write_text("{not json")
    assert cli.main([str(tmp_path), "--baseline", str(bad_bl)]) == cli.EXIT_USAGE
    # argparse's own usage failures are exit code 2 as well
    with pytest.raises(SystemExit) as exc:
        cli.main(["--format", "yaml"])
    assert exc.value.code == cli.EXIT_USAGE


def test_cli_json_schema(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(_FINDING_SNIPPET)
    rc = cli.main([str(tmp_path), "--format", "json"])
    assert rc == cli.EXIT_FINDINGS
    doc = json.loads(capsys.readouterr().out)
    assert doc["version"] == cli.JSON_SCHEMA_VERSION
    assert set(doc) == {
        "version", "findings", "baselined", "stale_baseline_entries", "summary",
    }
    (finding,) = doc["findings"]
    assert set(finding) == {
        "rule", "path", "line", "col", "message", "symbol", "fingerprint",
        "detail",
    }
    assert finding["rule"] == "swallowed-exception"
    assert doc["summary"] == {"count": 1, "by_rule": {"swallowed-exception": 1}}


def test_cli_baseline_flow_end_to_end(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(_FINDING_SNIPPET)
    bl = tmp_path / "analysis_baseline.json"
    assert cli.main([str(tmp_path), "--write-baseline", str(bl)]) == cli.EXIT_FINDINGS
    data = json.loads(bl.read_text())
    data["entries"][0]["justification"] = "known, accepted, tracked here"
    bl.write_text(json.dumps(data))
    capsys.readouterr()
    assert cli.main([str(tmp_path), "--baseline", str(bl)]) == cli.EXIT_CLEAN
    assert "1 baselined" in capsys.readouterr().err


def test_cli_list_rules_names_all_six(capsys):
    assert cli.main(["--list-rules"]) == cli.EXIT_CLEAN
    out = capsys.readouterr().out
    for rule in (
        "jit-purity", "use-after-donation", "host-sync-in-loop",
        "lock-discipline", "metric-name-consistency", "swallowed-exception",
        "wall-clock-deadline",
    ):
        assert rule in out


def test_baseline_entry_absorbs_at_most_one_finding(tmp_path):
    """Two identical violations in one symbol share a fingerprint; the
    single justified entry must NOT hide the second one."""
    findings = lint_code(
        tmp_path,
        """
        def a(x, y):
            try:
                x()
            except Exception:
                pass
            try:
                y()
            except Exception:
                pass
        """,
        rule="swallowed-exception",
    )
    assert len(findings) == 2
    assert findings[0].fingerprint == findings[1].fingerprint
    bl_path = tmp_path / "baseline.json"
    baseline_mod.write(bl_path, findings[:1])
    data = json.loads(bl_path.read_text())
    data["entries"][0]["justification"] = "the first one is fine"
    bl_path.write_text(json.dumps(data))
    new, baselined, stale = baseline_mod.Baseline.load(bl_path).split(findings)
    assert len(baselined) == 1 and len(new) == 1 and stale == []


def test_engine_rejects_undecodable_file_as_usage_error(tmp_path, capsys):
    bad = tmp_path / "latin.py"
    bad.write_bytes(b"x = '\xe9'\n")  # latin-1 bytes, no coding cookie
    with pytest.raises(engine.ParseError):
        engine.run([bad], root=tmp_path)
    assert cli.main([str(bad)]) == cli.EXIT_USAGE
    # A PEP 263 cookie makes the same bytes legal — and lintable.
    ok = tmp_path / "cookied.py"
    ok.write_bytes(b"# -*- coding: latin-1 -*-\nx = '\xe9'\n")
    assert engine.run([ok], root=tmp_path) == []


def test_engine_rejects_null_bytes_as_usage_error(tmp_path):
    bad = tmp_path / "nul.py"
    bad.write_bytes(b"x = 1\x00\n")
    with pytest.raises(engine.ParseError):
        engine.run([bad], root=tmp_path)
    assert cli.main([str(bad)]) == cli.EXIT_USAGE


def test_cli_rules_subset_does_not_call_other_entries_stale(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(_FINDING_SNIPPET)
    bl = tmp_path / "analysis_baseline.json"
    baseline_mod.write(bl, engine.run([tmp_path], root=tmp_path))
    data = json.loads(bl.read_text())
    data["entries"][0]["justification"] = "accepted"
    bl.write_text(json.dumps(data))
    capsys.readouterr()
    # jit-purity alone can't see the swallowed-exception finding; its
    # baseline entry must not be reported as deletable.
    assert cli.main([str(tmp_path), "--rules", "jit-purity"]) == cli.EXIT_CLEAN
    assert "stale" not in capsys.readouterr().err


def test_engine_deduplicates_overlapping_targets(tmp_path):
    (tmp_path / "m.py").write_text(_FINDING_SNIPPET)
    findings = engine.run([tmp_path, tmp_path / "m.py"], root=tmp_path)
    assert len(findings) == 1


def test_donation_cleared_by_non_call_rebind(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import jax

        def train(f, other_fn, x, y):
            g = jax.jit(f, donate_argnums=(0,))
            g = other_fn       # no longer the donating callable
            g(x, y)
            return x.shape     # fine: nothing was donated
        """,
        rule="use-after-donation",
    )
    assert findings == []


def test_jit_purity_time_requires_stdlib_import(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import jax

        @jax.jit
        def step(state, time):
            return time.mean()  # `time` is an array argument here
        """,
        rule="jit-purity",
    )
    assert findings == []


def test_jit_purity_must_not_flag_at_set_or_factory_helpers(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import jax

        @jax.jit
        def step(metrics, loss):
            return metrics.at[0].set(loss)  # pure functional update

        def make_train_step(cfg):
            def build_schedule():
                print("runs ONCE at factory time, never traced")
                return cfg
            schedule = build_schedule()

            def train_step(state, batch):
                return state
            return train_step
        """,
        rule="jit-purity",
    )
    assert findings == []


def test_host_sync_must_not_flag_jnp_asarray(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import jax.numpy as jnp

        def train(step, state, batches):
            for batch in batches:
                state, metrics = step(state, batch)
                staged = jnp.asarray(metrics["loss"])  # device op, no sync
            return state
        """,
        rule="host-sync-in-loop",
    )
    assert findings == []


def test_swallowed_exception_flags_tuple_clause(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        def a():
            try:
                return 1
            except (Exception, ValueError):
                pass
        """,
        rule="swallowed-exception",
    )
    assert len(findings) == 1


def test_write_baseline_preserves_existing_justifications(tmp_path):
    (tmp_path / "bad.py").write_text(_FINDING_SNIPPET)
    findings = engine.run([tmp_path], root=tmp_path)
    bl = tmp_path / "bl.json"
    baseline_mod.write(bl, findings)
    data = json.loads(bl.read_text())
    data["entries"][0]["justification"] = "human-written, must survive"
    # An unrelated justified entry a partial run can't see must survive too.
    data["entries"].append({
        "rule": "jit-purity", "path": "other.py", "symbol": "f",
        "message": "elsewhere", "justification": "also accepted",
    })
    bl.write_text(json.dumps(data))
    baseline_mod.write(bl, findings)  # regenerate
    regen = json.loads(bl.read_text())
    justs = {e["justification"] for e in regen["entries"]}
    assert justs == {"human-written, must survive", "also accepted"}


def test_metric_consistency_docs_match_is_whole_word(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        from hops_tpu.telemetry.metrics import REGISTRY

        c = REGISTRY.counter("hops_tpu_feed", "Truncated name")
        """,
        rule="metric-name-consistency",
        docs="only `hops_tpu_feed_batches_total` is documented",
    )
    assert len(findings) == 1
    assert "hops_tpu_feed" in findings[0].message


# -- docs rendering -----------------------------------------------------------


def test_make_renders_analysis_doc_pages():
    """Every analysis module yields a docs-site page (make.py walks the
    package; this pins the new subtree in)."""
    import sys

    sys.path.insert(0, str(Path(analysis.__file__).parents[2]))
    import make

    pkg = Path(analysis.__file__).parent
    for mod in sorted(pkg.rglob("*.py")):
        rendered = make.render_module(mod)
        assert rendered is not None, f"{mod} rendered no docs page"
        page, first_line = rendered
        assert first_line, f"{mod} docstring first line empty"


# -- unbounded-priority-queue -------------------------------------------------


def test_unbounded_priority_queue_flags_boundless_constructions(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        import queue
        from hops_tpu.runtime import qos

        pq = queue.PriorityQueue()
        bpq = qos.BoundedPriorityQueue(maxsize=None)
        zero = qos.BoundedPriorityQueue(0)
        """,
        rule="unbounded-priority-queue",
        filename=FLEET_FILE,
    )
    assert rule_names(findings) == ["unbounded-priority-queue"] * 3


def test_unbounded_priority_queue_accepts_bounds_and_config_names(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        import queue
        from hops_tpu.runtime import qos

        ok1 = queue.PriorityQueue(128)
        ok2 = qos.BoundedPriorityQueue(1024, starvation_limit=8)
        bound = int(cfg.get("queue_bound", 1024))
        ok3 = qos.BoundedPriorityQueue(bound)
        """,
        rule="unbounded-priority-queue",
        filename="hops_tpu/modelrepo/serving.py",
    )
    assert findings == []


def test_unbounded_priority_queue_scoped_to_serving_tiers(tmp_path):
    code = """
    import queue

    pq = queue.PriorityQueue()
    """
    (tmp_path / "hops_tpu" / "jobs").mkdir(parents=True)
    (tmp_path / "hops_tpu" / "modelrepo").mkdir(parents=True)
    assert lint_code(tmp_path, code, rule="unbounded-priority-queue",
                     filename="hops_tpu/jobs/dag_helper.py") == []
    flagged = lint_code(tmp_path, code, rule="unbounded-priority-queue",
                        filename="hops_tpu/modelrepo/lm_engine.py")
    assert rule_names(flagged) == ["unbounded-priority-queue"]


# -- adhoc-http-server --------------------------------------------------------


def test_adhoc_http_server_flags_instantiation_and_subclass(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        import http.server
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                pass

        srv = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        srv2 = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        """,
        rule="adhoc-http-server",
        filename="hops_tpu/modelrepo/newthing.py",
    )
    assert rule_names(findings) == ["adhoc-http-server"] * 3
    assert any("subclasses" in f.message for f in findings)


def test_adhoc_http_server_sanctioned_core_exempt(tmp_path):
    (tmp_path / "hops_tpu" / "runtime").mkdir(parents=True)
    code = """
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _H(BaseHTTPRequestHandler):
        pass

    baseline = ThreadingHTTPServer(("127.0.0.1", 0), _H)
    """
    assert lint_code(tmp_path, code, rule="adhoc-http-server",
                     filename="hops_tpu/runtime/httpserver.py") == []
    flagged = lint_code(tmp_path, code, rule="adhoc-http-server",
                        filename="hops_tpu/runtime/other.py")
    assert len(flagged) == 2


def test_adhoc_http_server_allows_annotations_and_own_core(tmp_path):
    """Type annotations on embedder shims (telemetry/export.py keeps
    stdlib-handler wrappers) and the event-loop core's own identically
    named HTTPServer class must not be flagged."""
    (tmp_path / "hops_tpu" / "telemetry").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        from http.server import BaseHTTPRequestHandler

        from hops_tpu.runtime.httpserver import HTTPServer

        def handle_metrics_path(handler: BaseHTTPRequestHandler) -> bool:
            return False

        srv = HTTPServer(lambda m, p, h, b: (200, {}, b""), name="metrics")
        """,
        rule="adhoc-http-server",
        filename="hops_tpu/telemetry/export.py",
    )
    assert findings == []


def test_adhoc_http_server_stdlib_httpserver_import_disambiguates(tmp_path):
    """Bare ``HTTPServer(...)`` is flagged exactly when the file
    imported it from http.server — the stdlib class, not the core."""
    (tmp_path / "hops_tpu" / "jobs").mkdir(parents=True)
    flagged = lint_code(
        tmp_path,
        """
        from http.server import HTTPServer

        srv = HTTPServer(("127.0.0.1", 0), None)
        """,
        rule="adhoc-http-server",
        filename="hops_tpu/jobs/snip.py",
    )
    assert rule_names(flagged) == ["adhoc-http-server"]


def test_adhoc_http_server_tree_is_clean():
    """All five server sites ride the event-loop core now — zero
    findings, no baseline entries (the migration IS complete)."""
    from hops_tpu.analysis.cli import default_target, lint_root

    pkg = default_target()
    root = lint_root([pkg])
    rules = [r for r in engine.all_rules() if r.name == "adhoc-http-server"]
    findings = engine.run([pkg], root=root, rules=rules)
    assert findings == [], "\n".join(f.render() for f in findings)


# -- hardcoded-loopback -------------------------------------------------------


def test_hardcoded_loopback_flags_url_literals_and_fstrings(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        def predict(port, body):
            url = f"http://127.0.0.1:{port}/predict"
            return post(url, body)

        FALLBACK = "http://localhost:9000/v1/models/m:predict"
        """,
        rule="hardcoded-loopback",
        filename=FLEET_FILE,
    )
    assert rule_names(findings) == ["hardcoded-loopback"] * 2
    assert "registered" in findings[0].message


def test_hardcoded_loopback_must_not_flag_binds_defaults_or_logs(tmp_path):
    (tmp_path / "hops_tpu" / "modelrepo" / "fleet").mkdir(parents=True)
    findings = lint_code(
        tmp_path,
        """
        from http.server import ThreadingHTTPServer

        def serve(port, handler):
            # Binding a local server to loopback is correct — only a
            # URL pins where a REQUEST goes.
            return ThreadingHTTPServer(("127.0.0.1", port), handler)

        def connect(host="127.0.0.1", port=0):
            log.info("replica on %s:%d (localhost)", host, port)
            return (host, port)
        """,
        rule="hardcoded-loopback",
        filename=FLEET_FILE,
    )
    assert findings == []


def test_hardcoded_loopback_scoped_to_multi_host_paths(tmp_path):
    code = """
    PROBE = "http://127.0.0.1:9090/healthz"
    """
    # httpclient is host-agnostic plumbing: callers pass full URLs, so a
    # loopback literal there is a test fixture, not a routing decision.
    (tmp_path / "hops_tpu" / "runtime").mkdir(parents=True)
    (tmp_path / "hops_tpu" / "featurestore").mkdir(parents=True)
    assert lint_code(tmp_path, code, rule="hardcoded-loopback",
                     filename="hops_tpu/runtime/httpclient.py") == []
    flagged = lint_code(tmp_path, code, rule="hardcoded-loopback",
                        filename="hops_tpu/featurestore/online_serving.py")
    assert rule_names(flagged) == ["hardcoded-loopback"]


# -- whole-program concurrency rules ------------------------------------------


def lint_tree(tmp_path: Path, files: dict[str, str], rule: str | None = None):
    """Write several modules into one scratch tree and lint them together
    (the concurrency rules are whole-program: identity and call edges
    span files)."""
    for name, code in files.items():
        target = tmp_path / name
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(textwrap.dedent(code))
    rules = None
    if rule is not None:
        rules = [r for r in engine.all_rules() if r.name == rule]
        assert rules, f"unknown rule {rule}"
    return engine.run([tmp_path], root=tmp_path, rules=rules)


def test_lock_order_inversion_flags_ab_ba(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import threading

        _registry_lock = threading.Lock()
        _cache_lock = threading.Lock()

        def publish(entry):
            with _registry_lock:
                with _cache_lock:
                    return entry

        def evict(key):
            with _cache_lock:
                with _registry_lock:
                    return key
        """,
        rule="lock-order-inversion",
    )
    assert rule_names(findings) == ["lock-order-inversion"]
    (f,) = findings
    assert "_registry_lock" in f.message and "_cache_lock" in f.message
    assert "publish" in f.message and "evict" in f.message
    # Both acquisition chains land in the detail as file:line steps —
    # and the detail is rendered, but excluded from the fingerprint.
    assert "snip.py:" in f.detail and "conflicting order" in f.detail
    assert f.detail.splitlines()[1] in f.render()


def test_lock_order_inversion_cross_file_needs_whole_program(tmp_path):
    """The AB half lives in liba, the BA half in libb, joined by calls:
    either file alone is provably clean — only the whole-program graph
    closes the cycle."""
    files = {
        "liba.py": """
            import threading
            import libb

            LOCK_A = threading.Lock()

            def grab_a():
                with LOCK_A:
                    pass

            def renew():
                with LOCK_A:
                    libb.flush()
            """,
        "libb.py": """
            import threading
            import liba

            LOCK_B = threading.Lock()

            def flush():
                with LOCK_B:
                    pass

            def audit():
                with LOCK_B:
                    liba.grab_a()
            """,
    }
    findings = lint_tree(tmp_path, files, rule="lock-order-inversion")
    assert rule_names(findings) == ["lock-order-inversion"]
    assert "liba.py:LOCK_A" in findings[0].message
    assert "libb.py:LOCK_B" in findings[0].message
    # Single-file runs cannot see the other half of the cycle.
    one = engine.run([tmp_path / "liba.py"], root=tmp_path,
                     rules=[r for r in engine.all_rules()
                            if r.name == "lock-order-inversion"])
    other = engine.run([tmp_path / "libb.py"], root=tmp_path,
                       rules=[r for r in engine.all_rules()
                              if r.name == "lock-order-inversion"])
    assert one == [] and other == []


def test_lock_order_inversion_must_not_flag_sanctioned_shapes(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()
        _r = threading.RLock()

        def first(x):
            # Consistent order everywhere: no cycle.
            with _a:
                with _b:
                    return x

        def second(x):
            with _a:
                with _b:
                    return x + 1

        def reenter(x):
            # Same-lock re-entry is RLock territory, not an inversion.
            with _r:
                with _r:
                    return x

        def local_locks(other):
            # Anonymous locals have no global identity; they must not
            # fabricate graph nodes.
            mine = threading.Lock()
            with mine:
                with other:
                    return True
        """,
        rule="lock-order-inversion",
    )
    assert findings == []


def test_blocking_under_lock_flags_direct_and_interprocedural(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import threading
        import time
        from urllib.request import urlopen

        class Store:
            def __init__(self):
                self._lock = threading.Lock()

            def slow_probe(self):
                with self._lock:
                    time.sleep(0.5)

            def refresh(self):
                with self._lock:
                    return self._dial()

            def _dial(self):
                return urlopen("http://example/health").read()
        """,
        rule="blocking-under-lock",
    )
    assert rule_names(findings) == ["blocking-under-lock"] * 2
    direct, via_call = sorted(findings, key=lambda f: f.line)
    assert "time.sleep" in direct.message
    assert "Store._lock" in direct.message
    # The interprocedural one names the blocking op, not the call site's
    # innocent-looking helper, and carries the witness chain.
    assert "urlopen" in via_call.message
    assert "_dial" in via_call.detail
    assert via_call.detail.count("snip.py:") >= 2


def test_blocking_under_lock_must_not_flag_sanctioned_shapes(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import threading
        import time

        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()

            def snapshot_then_wait(self):
                with self._lock:
                    state = dict(vars(self))
                # Blocking OUTSIDE the critical section is the fix shape.
                time.sleep(0.01)
                return state

            def consume(self):
                # cv.wait under its own cv releases the lock: sanctioned.
                with self._cv:
                    while not getattr(self, "_ready", False):
                        self._cv.wait()
        """,
        rule="blocking-under-lock",
    )
    assert findings == []


def test_blocking_under_lock_foreign_lock_across_wait_still_flagged(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import threading

        class Pipe:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition()

            def drain(self):
                with self._lock:
                    with self._cv:
                        self._cv.wait()
        """,
        rule="blocking-under-lock",
    )
    # The wait waives _cv (it releases it) but NOT the outer _lock.
    assert rule_names(findings) == ["blocking-under-lock"]
    assert "Pipe._lock" in findings[0].message
    assert "Condition.wait" in findings[0].message


def test_event_loop_stall_flags_blocking_reachable_from_select(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import selectors
        import time

        class Server:
            def __init__(self):
                self._sel = selectors.DefaultSelector()

            def _io_loop(self):
                while True:
                    for key, _ in self._sel.select(0.1):
                        self._on_ready(key)

            def _on_ready(self, key):
                self._handle(key)

            def _handle(self, key):
                time.sleep(0.1)
        """,
        rule="event-loop-stall",
    )
    assert rule_names(findings) == ["event-loop-stall"]
    (f,) = findings
    assert "_io_loop" in f.message and "time.sleep" in f.message
    # The witness chain walks root -> _on_ready -> _handle -> sleep.
    assert "_on_ready" in f.detail and "_handle" in f.detail


def test_event_loop_stall_worker_dispatch_is_clean(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import selectors
        import time
        from concurrent.futures import ThreadPoolExecutor

        class Server:
            def __init__(self):
                self._sel = selectors.DefaultSelector()
                self._pool = ThreadPoolExecutor(4)

            def _io_loop(self):
                while True:
                    for key, _ in self._sel.select(0.1):
                        self._on_ready(key)

            def _on_ready(self, key):
                # Handoff: the blocking handler runs on a worker thread,
                # not the IO loop — the sanctioned escape.
                self._pool.submit(self._handle, key)

            def _handle(self, key):
                time.sleep(0.1)
        """,
        rule="event-loop-stall",
    )
    assert findings == []


# -- CLI: --only / --changed / --graph / grouped stale report -----------------


def test_cli_only_is_an_alias_for_rules(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(_FINDING_SNIPPET)
    assert cli.main([str(tmp_path), "--only", "swallowed-exception"]) \
        == cli.EXIT_FINDINGS
    assert cli.main([str(tmp_path), "--only", "jit-purity"]) == cli.EXIT_CLEAN
    assert cli.main([str(tmp_path), "--only", "nope"]) == cli.EXIT_USAGE


def _git(tmp_path, *args):
    import subprocess

    return subprocess.run(
        ["git", "-C", str(tmp_path), "-c", "user.email=t@t", "-c",
         "user.name=t", *args],
        capture_output=True, text=True, check=True,
    )


def test_cli_changed_lints_only_changed_files(tmp_path, capsys):
    clean = tmp_path / "committed.py"
    clean.write_text(_FINDING_SNIPPET)  # committed finding: out of scope
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    capsys.readouterr()
    assert cli.main([str(tmp_path), "--changed"]) == cli.EXIT_CLEAN
    assert "no changed files" in capsys.readouterr().err
    # An untracked file with a finding IS in scope...
    (tmp_path / "fresh.py").write_text(_FINDING_SNIPPET)
    capsys.readouterr()
    assert cli.main([str(tmp_path), "--changed"]) == cli.EXIT_FINDINGS
    out = capsys.readouterr().out
    assert "fresh.py" in out and "committed.py" not in out


def test_cli_changed_outside_git_is_usage_error(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    assert cli.main([str(tmp_path), "--changed"]) == cli.EXIT_USAGE
    assert "--changed" in capsys.readouterr().err


def test_cli_changed_keeps_project_rules_sound(tmp_path, capsys):
    """--changed must report a cross-file inversion whose OTHER half is
    unchanged: project rules analyze the full tree and only the
    reporting is filtered."""
    (tmp_path / "libb.py").write_text(textwrap.dedent("""
        import threading
        import liba

        LOCK_B = threading.Lock()

        def flush():
            with LOCK_B:
                pass

        def audit():
            with LOCK_B:
                liba.grab_a()
        """))
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (tmp_path / "liba.py").write_text(textwrap.dedent("""
        import threading
        import libb

        LOCK_A = threading.Lock()

        def grab_a():
            with LOCK_A:
                pass

        def renew():
            with LOCK_A:
                libb.flush()
        """))
    capsys.readouterr()
    assert cli.main(
        [str(tmp_path), "--changed", "--only", "lock-order-inversion"]
    ) == cli.EXIT_FINDINGS
    assert "lock-order inversion" in capsys.readouterr().out


def test_cli_graph_lock_json_and_dot(tmp_path, capsys):
    (tmp_path / "m.py").write_text(textwrap.dedent("""
        import threading

        _a = threading.Lock()
        _b = threading.RLock()

        def nest():
            with _a:
                with _b:
                    pass
        """))
    assert cli.main([str(tmp_path), "--graph", "lock", "--format", "json"]) \
        == cli.EXIT_CLEAN
    doc = json.loads(capsys.readouterr().out)
    assert {l["id"]: l["kind"] for l in doc["locks"]} == {
        "m.py:_a": "lock", "m.py:_b": "rlock",
    }
    (edge,) = doc["edges"]
    assert edge["from"] == "m.py:_a" and edge["to"] == "m.py:_b"
    assert edge["function"] == "nest"
    assert all({"path", "line", "step"} <= set(s) for s in edge["chain"])
    assert cli.main([str(tmp_path), "--graph", "lock"]) == cli.EXIT_CLEAN
    dot = capsys.readouterr().out
    assert dot.startswith("digraph lock_order {")
    assert '"m.py:_a" -> "m.py:_b"' in dot


def test_cli_stale_entries_grouped_by_rule(tmp_path, capsys):
    (tmp_path / "clean.py").write_text("x = 1\n")
    bl = tmp_path / "analysis_baseline.json"
    entries = [
        {"rule": "jit-purity", "path": "a.py", "symbol": "f",
         "message": "m1", "justification": "was real once"},
        {"rule": "jit-purity", "path": "b.py", "symbol": "g",
         "message": "m2", "justification": "was real once"},
        {"rule": "swallowed-exception", "path": "c.py", "symbol": "h",
         "message": "m3", "justification": "was real once"},
    ]
    bl.write_text(json.dumps({"version": 1, "entries": entries}))
    capsys.readouterr()
    assert cli.main([str(tmp_path)]) == cli.EXIT_CLEAN
    err = capsys.readouterr().err
    assert "warning: 3 stale baseline entrie(s)" in err
    # Grouped by rule, biggest group first, entries indented beneath.
    assert err.index("jit-purity: 2") < err.index("swallowed-exception: 1")
    assert "    a.py [f]: m1" in err
    assert "3 stale baseline entrie(s)" in err.splitlines()[-1]


def test_group_stale_orders_by_count_then_name():
    stale = [
        {"rule": "b"}, {"rule": "a"}, {"rule": "c"}, {"rule": "a"},
    ]
    grouped = baseline_mod.group_stale(stale)
    assert [(r, len(es)) for r, es in grouped] == [("a", 2), ("b", 1), ("c", 1)]


# -- wall-clock-deadline ------------------------------------------------------


def test_wall_clock_deadline_flags_compares_and_add_mints(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import time

        def wait(timeout_s):
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                pass

        def expired(start, ttl):
            return time.time() - start > ttl
        """,
        rule="wall-clock-deadline",
    )
    assert rule_names(findings) == ["wall-clock-deadline"] * 3
    assert all("monotonic" in f.message for f in findings)


def test_wall_clock_deadline_must_not_flag_timestamps_or_monotonic(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import time

        def stamp(rec):
            # Display/storage timestamps are what time.time() is FOR.
            rec["started_at"] = time.time()
            log.info("done in %.1fs", time.time() - rec["started_at"])
            return rec

        def wait(timeout_s):
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                pass

        def approx(probe, t0):
            # time.time() buried in another call's argument list:
            # comparing that call's RESULT is not a wall-clock compare.
            assert probe(time.time() - t0, abs=2.0) is None
        """,
        rule="wall-clock-deadline",
    )
    assert findings == []


def test_wall_clock_deadline_inline_pragma(tmp_path):
    findings = lint_code(
        tmp_path,
        """
        import time

        def mtime_age_ok(path, ttl):
            # st_mtime IS wall clock: same-timeline compare, on purpose.
            return time.time() - path.stat().st_mtime > ttl  # graftlint: disable=wall-clock-deadline

        def mtime_age_bad(path, ttl):
            return time.time() - path.stat().st_mtime > ttl
        """,
        rule="wall-clock-deadline",
    )
    assert len(findings) == 1
    assert "mtime_age_bad" in (findings[0].symbol or "")


def test_wall_clock_deadline_tree_is_clean():
    """Every deadline/elapsed computation the package ships runs on
    time.monotonic() — zero findings, no baseline entries (the one
    sanctioned wall-vs-mtime compare carries an inline disable)."""
    from hops_tpu.analysis.cli import default_target, lint_root

    pkg = default_target()
    root = lint_root([pkg])
    rules = [r for r in engine.all_rules() if r.name == "wall-clock-deadline"]
    findings = engine.run([pkg], root=root, rules=rules)
    assert findings == [], "\n".join(f.render() for f in findings)
