"""Event-loop HTTP core transport tests: the edge cases the selector
loop must hold that thread-per-connection got for free (or never had).

- slowloris-shaped clients (headers dripped a byte at a time) are
  bounded by the partial-request clock, not the last-byte clock;
- pipelined requests answer strictly in request order even when an
  early request is slower than its successors;
- a client that disconnects mid-response takes down only its own
  connection;
- keep-alive reuse across 100 sequential requests rides ONE socket and
  the transport metrics account it;
- the fleet chaos leg: ``router.forward`` faults behave identically on
  the new core (retry-elsewhere, client sees latency only).

The five ported server sites' own behavior is pinned by their existing
suites (test_fleet, test_chaos, test_placement, test_online_serving);
this file owns the transport itself.
"""

from __future__ import annotations

import socket
import threading
import time

import pytest

from hops_tpu.runtime.httpclient import HTTPPool
from hops_tpu.runtime.httpserver import HeaderView, HTTPServer, assemble
from hops_tpu.telemetry.metrics import REGISTRY


def _echo_route(method, path, headers, body):
    payload = f"{method} {path} {len(body or b'')}".encode()
    return 200, {"Content-Type": "text/plain"}, payload


@pytest.fixture
def server():
    srv = HTTPServer(_echo_route, name="t-edge", idle_timeout_s=0.4)
    yield srv
    srv.stop()


def _connect(srv: HTTPServer) -> socket.socket:
    s = socket.create_connection((srv.host, srv.port), timeout=5.0)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def _read_response(s: socket.socket,
                   buf: bytearray | None = None) -> tuple[int, bytes]:
    """Read one Content-Length-framed response off a raw socket. Pass
    the SAME ``buf`` across calls when reading pipelined responses —
    bytes of response N+1 over-read while draining response N stay in
    it instead of being lost."""
    if buf is None:
        buf = bytearray()
    while b"\r\n\r\n" not in buf:
        chunk = s.recv(4096)
        if not chunk:
            raise ConnectionError(f"EOF mid-headers after {bytes(buf)!r}")
        buf += chunk
    head, _, rest = bytes(buf).partition(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1])
    length = 0
    for line in head.split(b"\r\n")[1:]:
        k, _, v = line.partition(b":")
        if k.strip().lower() == b"content-length":
            length = int(v.strip())
    rest = bytearray(rest)
    while len(rest) < length:
        chunk = s.recv(4096)
        if not chunk:
            raise ConnectionError("EOF mid-body")
        rest += chunk
    buf[:] = rest[length:]
    return status, bytes(rest[:length])


def _get(path: str, *, close: bool = False) -> bytes:
    extra = "Connection: close\r\n" if close else ""
    return (f"GET {path} HTTP/1.1\r\nHost: x\r\n{extra}\r\n").encode()


class TestSlowloris:
    def test_dripped_headers_evicted_by_partial_clock(self, server):
        """One header byte per poll keeps last_activity fresh forever;
        the partial-request clock must evict the connection anyway."""
        s = _connect(server)
        try:
            wire = _get("/drip")
            t0 = time.monotonic()
            dead = None
            for b in wire[:-1]:  # never complete the request
                try:
                    s.sendall(bytes([b]))
                except OSError:
                    dead = time.monotonic()
                    break
                time.sleep(0.05)
                if time.monotonic() - t0 > 10:
                    break
                # An evicted connection surfaces as EOF on read too.
                s.settimeout(0.01)
                try:
                    if s.recv(1) == b"":
                        dead = time.monotonic()
                        break
                except TimeoutError:
                    pass
                except OSError:
                    dead = time.monotonic()
                    break
            assert dead is not None, "slowloris drip was never evicted"
            # Evicted by the 0.4 s partial clock, well before the drip
            # could finish (and not instantly — a normal slow client
            # inside the window is fine; see the next test).
            assert 0.3 <= dead - t0 <= 5.0
        finally:
            s.close()

    def test_slow_but_inside_window_completes(self, server):
        """A request paused mid-headers SHORTER than the timeout is not
        a slowloris: it completes normally once the bytes arrive."""
        s = _connect(server)
        try:
            wire = _get("/slow")
            s.sendall(wire[:10])
            time.sleep(0.15)  # inside the 0.4 s window
            s.sendall(wire[10:])
            status, body = _read_response(s)
            assert (status, body) == (200, b"GET /slow 0")
        finally:
            s.close()

    def test_idle_keepalive_eventually_evicted(self, server):
        """A connection that completed a request and then goes silent
        is swept once idle_timeout_s passes."""
        s = _connect(server)
        try:
            s.sendall(_get("/one"))
            assert _read_response(s)[0] == 200
            time.sleep(1.0)  # > idle_timeout_s with no traffic
            s.settimeout(2.0)
            s.sendall(_get("/two"))
            with pytest.raises((ConnectionError, OSError)):
                _read_response(s)
        finally:
            s.close()


class TestPipelining:
    def test_responses_strictly_in_request_order(self):
        """Three pipelined requests where the FIRST is the slowest:
        responses must still come back 0, 1, 2 — later responses park
        until their predecessors are on the wire."""
        release = threading.Event()

        def route(method, path, headers, body):
            if path == "/slow":
                release.wait(timeout=10)
            return 200, {}, path.encode()

        srv = HTTPServer(route, name="t-pipe", workers=4)
        try:
            s = _connect(srv)
            s.sendall(_get("/slow") + _get("/b") + _get("/c"))
            time.sleep(0.2)  # /b and /c have finished their handlers
            release.set()
            rbuf = bytearray()
            got = [_read_response(s, rbuf) for _ in range(3)]
            assert [g[1] for g in got] == [b"/slow", b"/b", b"/c"]
            s.close()
        finally:
            srv.stop()

    def test_pipelined_metric_counts_overlap(self):
        srv = HTTPServer(_echo_route, name="t-pipemetric")
        m = REGISTRY.get("hops_tpu_http_pipelined_requests_total")
        base = m.value(server="t-pipemetric")
        try:
            s = _connect(srv)
            s.sendall(_get("/a") + _get("/b") + _get("/c"))
            rbuf = bytearray()
            for _ in range(3):
                assert _read_response(s, rbuf)[0] == 200
            s.close()
        finally:
            srv.stop()
        # At least the back-to-back tail arrived while earlier requests
        # were in flight (timing decides whether it is 1 or 2).
        assert m.value(server="t-pipemetric") - base >= 1


class TestMidResponseDisconnect:
    def test_disconnect_kills_only_its_own_connection(self):
        """A client that vanishes while its (large) response is being
        written must not disturb a neighbor on the same server."""
        big = b"x" * (8 * 1024 * 1024)  # larger than any socket buffer

        def route(method, path, headers, body):
            return 200, {}, big if path == "/big" else b"ok"

        srv = HTTPServer(route, name="t-disc")
        try:
            victim = _connect(srv)
            victim.sendall(_get("/big"))
            victim.recv(1024)  # the response started flowing
            victim.close()  # ... and the client is gone
            for _ in range(3):  # neighbor unaffected, repeatedly
                s = _connect(srv)
                s.sendall(_get("/ok", close=True))
                assert _read_response(s) == (200, b"ok")
                s.close()
        finally:
            srv.stop()

    def test_disconnect_before_handler_finishes(self):
        """Client sends a request and disconnects before the handler
        returns: the queued response hits a dead socket and the server
        shrugs (no handler crash, neighbors fine)."""
        gate = threading.Event()

        def route(method, path, headers, body):
            if path == "/wait":
                gate.wait(timeout=10)
            return 200, {}, b"done"

        srv = HTTPServer(route, name="t-disc2", workers=4)
        try:
            s = _connect(srv)
            s.sendall(_get("/wait"))
            time.sleep(0.1)
            s.close()  # gone before the response exists
            gate.set()
            time.sleep(0.2)
            s2 = _connect(srv)
            s2.sendall(_get("/after", close=True))
            assert _read_response(s2) == (200, b"done")
            s2.close()
        finally:
            srv.stop()


class TestKeepAliveReuse:
    def test_100_sequential_requests_one_socket(self):
        """The keep-alive contract, accounted: 100 requests ride ONE
        TCP connection and the transport metrics say so."""
        srv = HTTPServer(_echo_route, name="t-reuse")
        conns = REGISTRY.get("hops_tpu_http_connections_total")
        reqs = REGISTRY.get("hops_tpu_http_requests_total")
        reuse = REGISTRY.get("hops_tpu_http_keepalive_reuse_total")
        b_conns = conns.value(server="t-reuse")
        b_reqs = reqs.value(server="t-reuse")
        b_reuse = reuse.value(server="t-reuse")
        try:
            s = _connect(srv)
            for i in range(100):
                s.sendall(_get(f"/r{i}"))
                status, body = _read_response(s)
                assert status == 200
                assert body == f"GET /r{i} 0".encode()
            s.close()
        finally:
            srv.stop()
        assert conns.value(server="t-reuse") - b_conns == 1
        assert reqs.value(server="t-reuse") - b_reqs == 100
        assert reuse.value(server="t-reuse") - b_reuse == 99

    def test_connection_close_honored(self, server):
        s = _connect(server)
        try:
            s.sendall(_get("/bye", close=True))
            assert _read_response(s)[0] == 200
            s.settimeout(2.0)
            assert s.recv(1) == b""  # server closed after the response
        finally:
            s.close()

    def test_pool_pipeline_rides_one_connection(self):
        """HTTPPool.pipeline + the event-loop core: a whole batch on
        one pooled connection, answers in order, connection reused by
        the next batch."""
        srv = HTTPServer(_echo_route, name="t-poolpipe")
        pool = HTTPPool()
        try:
            reqs = [("GET", f"http://{srv.host}:{srv.port}/p{i}", None, None)
                    for i in range(8)]
            out = pool.pipeline(reqs, timeout_s=5.0)
            assert [b for _, b, _ in out] == [
                f"GET /p{i} 0".encode() for i in range(8)]
            out2 = pool.pipeline(reqs, timeout_s=5.0)
            assert len(out2) == 8
            assert pool.created == 1  # the second batch reused
        finally:
            pool.close()
            srv.stop()


class TestProtocolEdges:
    def test_malformed_request_line_gets_400_and_close(self, server):
        s = _connect(server)
        try:
            s.sendall(b"NONSENSE\r\n\r\n")
            status, _ = _read_response(s)
            assert status == 400
            s.settimeout(2.0)
            assert s.recv(1) == b""  # poisoned stream is closed
        finally:
            s.close()

    def test_chunked_transfer_encoding_refused(self, server):
        s = _connect(server)
        try:
            s.sendall(b"POST /x HTTP/1.1\r\nHost: x\r\n"
                      b"Transfer-Encoding: chunked\r\n\r\n")
            status, _ = _read_response(s)
            assert status == 400
        finally:
            s.close()

    def test_header_view_case_insensitive(self):
        hv = HeaderView({"content-type": "application/json", "x-a": "1"})
        assert hv["Content-Type"] == "application/json"
        assert hv.get("X-A") == "1"
        assert "CONTENT-TYPE" in hv
        assert hv.get("missing", "d") == "d"
        assert len(hv) == 2

    def test_assemble_never_copies_the_body(self):
        body = b'{"instances": [[1]]}'
        vec = assemble(200, {"Content-Type": "application/json"}, body)
        assert vec[1] is body  # zero-copy relay contract
        assert b"Content-Length: 20" in vec[0]

    def test_handler_exception_becomes_500(self):
        def route(method, path, headers, body):
            raise RuntimeError("boom")

        srv = HTTPServer(route, name="t-500")
        try:
            s = _connect(srv)
            s.sendall(_get("/x", close=True))
            status, body = _read_response(s)
            assert status == 500
            assert b"RuntimeError" in body
            s.close()
        finally:
            srv.stop()


class TestRouterForwardChaosOnNewCore:
    """The chaos leg the ISSUE names: ``router.forward`` faults on the
    event-loop transport behave exactly as on the old one — the
    injected failure strikes one replica, the request retries
    elsewhere, the client sees latency only."""

    def test_forward_fault_retries_elsewhere(self, workspace):
        import tempfile
        from pathlib import Path

        from hops_tpu.modelrepo import fleet, registry, serving
        from hops_tpu.runtime import faultinject

        d = Path(tempfile.mkdtemp(prefix="httpserver_chaos_"))
        (d / "p.py").write_text(
            "class Predict:\n"
            "    def predict(self, instances):\n"
            "        return [[v[0] * 2] for v in instances]\n")
        registry.export(d, "tchaos", metrics={"v": 1.0})
        serving.create_or_update("tchaos", model_name="tchaos",
                                 model_version=1, model_server="PYTHON")
        faultinject.disarm()
        try:
            with fleet.start_fleet("tchaos", 2, inprocess=True,
                                   scrape_interval_s=0.05) as f:
                assert f.predict([[1]])["predictions"] == [[2]]
                faultinject.arm("router.forward=error:OSError@times=1")
                assert f.predict([[4]])["predictions"] == [[8]]
                retried = REGISTRY.counter(
                    "hops_tpu_fleet_retries_total",
                    labels=("model", "reason")).value(
                        model="tchaos", reason="connect")
                assert retried >= 1
        finally:
            faultinject.disarm()
