"""Unattended hardware measurement sweep for the single-tenant TPU relay.

Runs, sequentially and with NO timeouts or kills (a killed client wedges
the relay — BENCHMARKS.md operational note), every measurement the round
needs on real hardware:

  1. relay health probe (kill-safe subprocess, bench.py --probe)
  2. decode_bench: base / int8 / GQA / window / int8+GQA+window
  3. decode_bench --valid-sweep (valid-length-proportional DMA check)
  4. headline ResNet-50 bench (bench.py), then its --remat A/B — LAST,
     because the relay has wedged itself on ResNet-sized compiles; the
     small decode measurements must already be banked by then

Each step's stdout+stderr and wall time append to HW_MEASURE.jsonl so a
later session (or a human) can transcribe the numbers into
BENCHMARKS.md even if this process's parent goes away. Steps run to
natural completion; a failed step records its output and the sweep
moves on.

Usage: nohup python hw_measure.py >> hw_measure.log 2>&1 &
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).parent
OUT = ROOT / "HW_MEASURE.jsonl"

# Small compiles FIRST: the relay has twice answered a ResNet-50-sized
# compile with a 25-min UNAVAILABLE and wedged itself afterwards
# (HW_MEASURE.jsonl 2026-07-31), so the decode measurements — tiny
# TransformerLM programs — must be banked before the big compile gets
# a chance to take the relay down.
STEPS: list[tuple[str, list[str]]] = [
    ("probe", [sys.executable, "bench.py", "--probe"]),
    ("decode_base", [sys.executable, "examples/decode_bench.py"]),
    ("decode_int8", [sys.executable, "examples/decode_bench.py", "--kv-dtype", "int8"]),
    ("decode_gqa", [sys.executable, "examples/decode_bench.py", "--kv-heads", "2"]),
    ("decode_window", [sys.executable, "examples/decode_bench.py", "--window", "256"]),
    ("decode_all_knobs", [sys.executable, "examples/decode_bench.py",
                          "--kv-dtype", "int8", "--kv-heads", "2", "--window", "256"]),
    ("valid_sweep", [sys.executable, "examples/decode_bench.py", "--valid-sweep"]),
    ("decode_continuous", [sys.executable, "examples/decode_bench.py", "--continuous",
                           "--batch", "4", "--tokens", "32", "--layers", "4"]),
    ("resnet50_bench", [sys.executable, "bench.py", "--no-probe"]),
    ("resnet50_bench_remat", [sys.executable, "bench.py", "--no-probe", "--remat"]),
]


def record(entry: dict) -> None:
    with OUT.open("a") as f:
        f.write(json.dumps(entry) + "\n")


def main() -> int:
    import os

    sys.path.insert(0, str(ROOT))
    from hops_tpu.runtime.relaylock import RelayBusy, relay_lock

    try:
        with relay_lock("hw_measure.py sweep"):
            # Snapshot the env AFTER acquiring: relay_lock exports the
            # pass-through token into os.environ, and children spawned
            # with a pre-acquisition copy would collide with our own
            # lock (subprocess env= replaces, not augments).
            env = dict(os.environ)
            # Children run scripts from examples/ — python puts the
            # SCRIPT's dir on sys.path, not the cwd, so the repo root
            # must ride PYTHONPATH (appended: /root/.axon_site must
            # stay first or the TPU plugin fails to register).
            env["PYTHONPATH"] = ":".join(
                p for p in (env.get("PYTHONPATH"), str(ROOT)) if p
            )
            return _run_steps(env)
    except RelayBusy as e:
        print(f"[hw_measure] {e}", flush=True)
        record({"step": "abort", "reason": f"relay lock busy: {e.owner}"})
        return 2


def _run_steps(env: dict) -> int:
    for name, cmd in STEPS:
        t0 = time.time()
        print(f"[hw_measure] {name}: {' '.join(cmd[1:])}", flush=True)
        proc = subprocess.run(  # no timeout, ever: let the relay finish
            cmd, cwd=ROOT, env=env, capture_output=True, text=True
        )
        entry = {
            "step": name,
            "rc": proc.returncode,
            "wall_s": round(time.time() - t0, 1),
            "stdout": proc.stdout[-4000:],
            "stderr": proc.stderr[-2000:],
            "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
        }
        record(entry)
        print(f"[hw_measure] {name}: rc={proc.returncode} in {entry['wall_s']}s", flush=True)
        if name == "probe" and '"ok": true' not in proc.stdout:
            record({"step": "abort", "reason": "relay unhealthy at probe"})
            print("[hw_measure] relay unhealthy — aborting sweep", flush=True)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
