"""Unattended hardware measurement sweep for the single-tenant TPU relay.

Runs, sequentially and with NO timeouts or kills (a killed client wedges
the relay — BENCHMARKS.md operational note), every measurement the round
needs on real hardware, under the relay lock:

  1. relay health probe (kill-safe subprocess, bench.py --probe); the
     sweep aborts here if the relay is wedged
  2. the shared round-5 queue (hw_steps.MEASUREMENT_STEPS — the same
     list hw_watch.py runs on recovery): int8 + composite decode knobs,
     the 16k valid-sweep, the continuous-batching A/Bs
     (h1/h8/spec/spec x h4/offline), then the two LARGE compiles last —
     bench.py --lm (~180M-param LM training headline) and the ResNet-50
     driver bench — because the relay has wedged itself on big compiles
     and the small decode evidence must already be banked by then
  3. bench-only extras: bf16/GQA/window decode baselines and the
     ResNet --remat A/B

Each step's stdout+stderr and wall time append to HW_MEASURE.jsonl so a
later session (or a human) can transcribe the numbers into
BENCHMARKS.md even if this process's parent goes away. Steps run to
natural completion; a failed step records its output and the sweep
moves on.

Usage: nohup python hw_measure.py >> hw_measure.log 2>&1 &
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

ROOT = Path(__file__).parent
OUT = ROOT / "HW_MEASURE.jsonl"

from hw_steps import MEASUREMENT_STEPS

# probe first (abort the sweep against a wedged relay), then the shared
# round-5 queue (hw_steps.py — same definition the watcher runs; its
# internal order banks small decode compiles before the wedge-prone
# large ones), then the lowest-priority extras LAST: re-confirmations
# of rows that already have green round-4 artifacts.
STEPS: list[tuple[str, list[str]]] = [
    ("probe", [sys.executable, "bench.py", "--probe"]),
    *MEASUREMENT_STEPS,
    ("decode_base", [sys.executable, "examples/decode_bench.py"]),
    ("decode_gqa", [sys.executable, "examples/decode_bench.py", "--kv-heads", "2"]),
    ("decode_window", [sys.executable, "examples/decode_bench.py", "--window", "256"]),
    ("resnet50_bench_remat", [sys.executable, "bench.py", "--no-probe", "--remat"]),
]


def record(entry: dict) -> None:
    with OUT.open("a") as f:
        f.write(json.dumps(entry) + "\n")


def main() -> int:
    import os

    sys.path.insert(0, str(ROOT))
    from hops_tpu.runtime.relaylock import RelayBusy, relay_lock

    try:
        with relay_lock("hw_measure.py sweep"):
            # Snapshot the env AFTER acquiring: relay_lock exports the
            # pass-through token into os.environ, and children spawned
            # with a pre-acquisition copy would collide with our own
            # lock (subprocess env= replaces, not augments).
            env = dict(os.environ)
            # Children run scripts from examples/ — python puts the
            # SCRIPT's dir on sys.path, not the cwd, so the repo root
            # must ride PYTHONPATH (appended: /root/.axon_site must
            # stay first or the TPU plugin fails to register).
            env["PYTHONPATH"] = ":".join(
                p for p in (env.get("PYTHONPATH"), str(ROOT)) if p
            )
            return _run_steps(env)
    except RelayBusy as e:
        print(f"[hw_measure] {e}", flush=True)
        record({"step": "abort", "reason": f"relay lock busy: {e.owner}"})
        return 2


def _run_steps(env: dict) -> int:
    for name, cmd in STEPS:
        t0 = time.time()
        print(f"[hw_measure] {name}: {' '.join(cmd[1:])}", flush=True)
        proc = subprocess.run(  # no timeout, ever: let the relay finish
            cmd, cwd=ROOT, env=env, capture_output=True, text=True
        )
        entry = {
            "step": name,
            "rc": proc.returncode,
            "wall_s": round(time.time() - t0, 1),
            "stdout": proc.stdout[-4000:],
            "stderr": proc.stderr[-2000:],
            "ts": time.strftime("%Y-%m-%d %H:%M:%S"),
        }
        record(entry)
        print(f"[hw_measure] {name}: rc={proc.returncode} in {entry['wall_s']}s", flush=True)
        if name == "probe" and '"ok": true' not in proc.stdout:
            record({"step": "abort", "reason": "relay unhealthy at probe"})
            print("[hw_measure] relay unhealthy — aborting sweep", flush=True)
            return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
